"""Bench harness: caching, checksum diff, compare_times format."""

import io
import os

import pytest

from dmlp_tpu.bench.configs import BenchConfig
from dmlp_tpu.bench.harness import (compare_times, ensure_input,
                                    ensure_oracle, run_config)


@pytest.fixture()
def tiny_cfg(monkeypatch):
    cfg = BenchConfig(1, 200, 20, 4, 0.0, 10.0, 1, 8, 4, 7, "tiny.in")
    monkeypatch.setitem(
        __import__("dmlp_tpu.bench.configs",
                   fromlist=["BENCH_CONFIGS"]).BENCH_CONFIGS, 1, cfg)
    return cfg


def test_input_generation_cached(tiny_cfg, tmp_path):
    d = str(tmp_path / "inputs")
    p1 = ensure_input(tiny_cfg, d)
    mtime = os.path.getmtime(p1)
    p2 = ensure_input(tiny_cfg, d)
    assert p1 == p2 and os.path.getmtime(p2) == mtime  # not regenerated
    with open(p1) as f:
        assert f.readline().strip() == "200 20 4"


def test_oracle_cached(tiny_cfg, tmp_path):
    inp = ensure_input(tiny_cfg, str(tmp_path / "inputs"))
    buf = io.StringIO()
    out1 = ensure_oracle(tiny_cfg, inp, str(tmp_path / "outputs"), buf)
    assert "cache" not in buf.getvalue()
    out2 = ensure_oracle(tiny_cfg, inp, str(tmp_path / "outputs"), buf)
    assert out1 == out2
    assert "Output found in cache. Skipping...\n" in buf.getvalue()


def test_run_config_end_to_end(tiny_cfg, tmp_path):
    buf = io.StringIO()
    res = run_config(1, base_dir=str(tmp_path), out=buf)
    assert res["checksums_match"], buf.getvalue()
    assert res["oracle_ms"] is not None and res["engine_ms"] is not None
    text = buf.getvalue()
    assert "Config 1: checksums PASS" in text
    assert "=== Performance Comparison ===" in text


def test_run_config_exact_mode(tiny_cfg, tmp_path):
    res = run_config(1, base_dir=str(tmp_path), fast=False,
                     out=io.StringIO())
    assert res["checksums_match"]


def test_run_config_profile_marker_on_cpu(tiny_cfg, tmp_path):
    """--profile on a CPU-pinned environment is a no-op with the explicit
    profile_unavailable marker in the config's RunRecord (ROADMAP open
    item 1: real-TPU runs get the linked jax.profiler capture instead)."""
    import json

    buf = io.StringIO()
    record_path = str(tmp_path / "runs.jsonl")
    res = run_config(1, base_dir=str(tmp_path), out=buf,
                     profile_dir=str(tmp_path / "prof"),
                     record_path=record_path)
    assert res["checksums_match"]
    assert "profile_unavailable" in buf.getvalue()
    rec = json.loads(open(record_path).read().splitlines()[-1])
    from dmlp_tpu.obs.run import SCHEMA_VERSION
    assert rec["schema"] == SCHEMA_VERSION
    assert rec["metrics"]["profile_unavailable"]
    assert "profile" not in rec.get("artifacts", {})


def test_compare_times_report_format():
    out = io.StringIO()
    pct = compare_times("Time taken: 100 ms\n", "Time taken: 80 ms\n", out)
    assert pct == pytest.approx(-20.0)
    assert "Benchmark time: 100 ms" in out.getvalue()
    assert "Engine time:    80 ms" in out.getvalue()
    assert "-20 ms (20.00% faster)" in out.getvalue()

    out = io.StringIO()
    pct = compare_times("Time taken: 80 ms\n", "Time taken: 100 ms\n", out)
    assert pct == pytest.approx(25.0)
    assert "+20 ms (25.00% slower)" in out.getvalue()

    out = io.StringIO()
    assert compare_times("nope\n", "Time taken: 1 ms\n", out) is None
    assert "Could not extract timing" in out.getvalue()


def _scrubbed_env():
    """Subprocess env for tests: CPU platform, no axon sitecustomize."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env


def test_engine_subprocess_timeout_kills(tiny_cfg, tmp_path):
    """A wedged engine must fail its config within the limit instead of
    blocking the suite — the mpirun --timeout 300 analog."""
    from dmlp_tpu.bench.harness import EngineTimeout, run_engine

    inp = ensure_input(tiny_cfg, str(tmp_path / "inputs"))
    with pytest.raises(EngineTimeout):
        # 10ms: the interpreter can't even finish importing -> guaranteed
        # timeout path, killed promptly.
        run_engine(tiny_cfg, inp, str(tmp_path), timeout_s=0.01,
                   env=_scrubbed_env())


def test_run_config_timeout_reports(tiny_cfg, tmp_path):
    buf = io.StringIO()
    res = run_config(1, base_dir=str(tmp_path), out=buf, timeout_s=0.01,
                     env=_scrubbed_env())
    assert res.get("timeout") is True
    assert not res["checksums_match"]
    assert "TIMEOUT" in buf.getvalue()


def test_mesh_shape_plumbed_to_cli(tmp_path):
    """BenchConfig.mesh_shape must reach the engine invocation (r1 VERDICT
    missing item 4: the declared mesh was dead config)."""
    from dmlp_tpu.bench.harness import run_engine

    cfg = BenchConfig(1, 64, 8, 3, 0.0, 10.0, 1, 6, 4, 7, "mesh.in",
                      mode="sharded", mesh_shape=(4, 2))
    inp = ensure_input(cfg, str(tmp_path / "inputs"))
    out_p, err_p = run_engine(cfg, inp, str(tmp_path), env=_scrubbed_env(),
                              timeout_s=240)
    with open(out_p) as f:
        assert "checksum:" in f.read()


def test_mesh_too_big_falls_back_with_warning(tmp_path):
    """A mesh hint needing more devices than the host has must degrade to
    the auto mesh (visible on stderr), not kill the config."""
    from dmlp_tpu.bench.harness import run_engine

    cfg = BenchConfig(1, 64, 8, 3, 0.0, 10.0, 1, 6, 4, 7, "mesh2.in",
                      mode="sharded", mesh_shape=(64, 2))
    inp = ensure_input(cfg, str(tmp_path / "inputs"))
    out_p, err_p = run_engine(cfg, inp, str(tmp_path), env=_scrubbed_env(),
                              timeout_s=240)
    with open(out_p) as f:
        assert "checksum:" in f.read()
    with open(err_p) as f:
        assert "using auto mesh" in f.read()


def test_run_config_engine_error_is_isolated(tiny_cfg, tmp_path):
    """A crashing engine fails its config but not the whole suite."""
    buf = io.StringIO()
    env = _scrubbed_env()
    env["PYTHONPATH"] = str(tmp_path)  # poison: break the subprocess import
    (tmp_path / "jax").mkdir()
    (tmp_path / "jax" / "__init__.py").write_text("raise ImportError('x')\n")
    res = run_config(1, base_dir=str(tmp_path), out=buf, env=env)
    assert res.get("error")
    assert not res["checksums_match"]
    assert "ERROR" in buf.getvalue()


def test_run_config_multiproc_cluster(monkeypatch, tmp_path):
    """Config 5 analog at tiny scale: a real 2-process Gloo cluster under
    the harness kill timeout, proc-0 stdout diffed against the oracle —
    the run_bench.sh multi-node flow end-to-end (VERDICT r2 item 4)."""
    cfg = BenchConfig(5, 180, 16, 4, 0.0, 10.0, 1, 8, 4, 7, "mp.in",
                      mode="sharded", procs=2, virtual_devices=4)
    monkeypatch.setitem(
        __import__("dmlp_tpu.bench.configs",
                   fromlist=["BENCH_CONFIGS"]).BENCH_CONFIGS, 5, cfg)
    buf = io.StringIO()
    res = run_config(5, base_dir=str(tmp_path), out=buf, timeout_s=240,
                     env=_scrubbed_env())
    assert res["checksums_match"], buf.getvalue()
    assert "Config 5: checksums PASS" in buf.getvalue()


def test_run_engine_passes_pallas_and_select(tmp_path):
    """use_pallas/select must reach the engine argv (VERDICT r2 item 3:
    the r2 harness always benched the default path)."""
    from dmlp_tpu.bench.harness import run_engine

    cfg = BenchConfig(1, 128, 8, 3, 0.0, 10.0, 1, 6, 4, 7, "ps.in",
                      use_pallas=True, select="seg")
    inp = ensure_input(cfg, str(tmp_path / "inputs"))
    out_p, err_p = run_engine(cfg, inp, str(tmp_path), env=_scrubbed_env(),
                              timeout_s=240)
    with open(out_p) as f:
        assert "checksum:" in f.read()


def test_oracle_capture_kit_diff_roundtrip(tmp_path):
    """VERDICT r4 item 5 (repo side): simulate a capture directory whose
    'oracle binary' outputs come from the golden model, and assert
    tools/oracle_diff.py accepts it — and rejects a corrupted checksum
    and a mismatched input hash. (The capture script itself needs an
    x86+OpenMPI host; its manifest format is pinned here.)"""
    import hashlib
    import json
    import subprocess
    import sys

    from dmlp_tpu.bench.configs import BENCH_CONFIGS
    from dmlp_tpu.bench.harness import ensure_input
    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.io.grammar import parse_input
    from dmlp_tpu.io.report import format_results

    cap = tmp_path / "cap"
    cap.mkdir()
    cfg = BENCH_CONFIGS[1]
    inp_path = ensure_input(cfg, str(cap))
    with open(inp_path, "rb") as f:
        raw = f.read()
    with open(inp_path, "rb") as f:
        results = knn_golden_fast(parse_input(f))
    (cap / "oracle_1.out").write_text(format_results(results) + "\n")
    manifest = {"configs": {"1": {
        "bench": "bench_1", "input": cfg.input_name,
        "input_sha256": hashlib.sha256(raw).hexdigest(),
        "np": 8, "time_taken_ms": 1234, "out_file": "oracle_1.out"}}}
    mpath = cap / "ORACLE_GOLDEN.json"
    mpath.write_text(json.dumps(manifest))

    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "oracle_diff.py")
    env = {**os.environ}
    r = subprocess.run([sys.executable, tool, str(mpath), "--configs", "1"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "config 1: OK" in r.stdout

    # Corrupt one checksum -> must fail with a differing count.
    out = (cap / "oracle_1.out").read_text().splitlines()
    q, c = out[0].rsplit(" ", 1)[0], out[0].rsplit(" ", 1)[1]
    out[0] = f"{q} {int(c) ^ 1}"
    (cap / "oracle_1.out").write_text("\n".join(out) + "\n")
    r = subprocess.run([sys.executable, tool, str(mpath), "--configs", "1"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 1 and "MISMATCH" in r.stdout

    # Wrong input hash -> generator-divergence failure.
    manifest["configs"]["1"]["input_sha256"] = "0" * 64
    mpath.write_text(json.dumps(manifest))
    r = subprocess.run([sys.executable, tool, str(mpath), "--configs", "1"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 1 and "INPUT MISMATCH" in r.stdout


def test_run_config_timeout_records_marker_not_gate(tiny_cfg, tmp_path):
    """Resilience satellite: a hung config documents itself with the
    explicit `timed_out` marker (markers never gate, PR 5 convention)
    and the bench run's verdict ignores it."""
    buf = io.StringIO()
    res = run_config(1, base_dir=str(tmp_path), out=buf, timeout_s=0.01,
                     env=_scrubbed_env())
    assert res.get("timed_out") is True
    assert res.get("timeout") is True          # legacy spelling kept
    # the main() gate treats timed_out as non-gating:
    assert res["checksums_match"] or res.get("timed_out", False)


def test_per_config_timeout_override(tiny_cfg, tmp_path, monkeypatch):
    """BenchConfig.timeout_s beats the harness-wide --timeout."""
    import dataclasses

    from dmlp_tpu.bench import configs as bench_configs
    cfg = dataclasses.replace(tiny_cfg, timeout_s=0.01)
    monkeypatch.setitem(bench_configs.BENCH_CONFIGS, 1, cfg)
    buf = io.StringIO()
    res = run_config(1, base_dir=str(tmp_path), out=buf, timeout_s=600.0,
                     env=_scrubbed_env())
    assert res.get("timed_out") is True        # 600s harness limit unused


def test_run_config_fused_ab_records_and_checks_identity(monkeypatch,
                                                         tmp_path):
    """ISSUE 8: ``fused_ab=True`` runs interleaved DMLP_TPU_FUSED=1/0
    engine pairs, verifies the arms byte-identical (and equal to the
    oracle in exact mode), CONFIRMS the fused arm actually dispatched
    the fused kernel (extract_impl via the metrics channel), and
    records both medians with raw per-rep lists — the ledger's
    per-trial evidence for the fused series."""
    from dmlp_tpu.bench import configs as bench_configs
    cfg = BenchConfig(1, 900, 12, 4, -20.0, 20.0, 1, 28, 5, 7, "tiny.in",
                      use_pallas=True, select="extract")
    monkeypatch.setitem(bench_configs.BENCH_CONFIGS, 1, cfg)
    buf = io.StringIO()
    res = run_config(1, base_dir=str(tmp_path), out=buf,
                     env=_scrubbed_env(), fused_ab=True)
    assert res["checksums_match"], buf.getvalue()
    assert res.get("fused_ab_identical") is True, res
    assert res["fused_ab_impls"]["fused"] == ["fused"]
    assert res["fused_ab_impls"]["two_pass"] == ["extract"]
    assert isinstance(res["engine_ms_fused"], int)
    assert isinstance(res["engine_ms_two_pass"], int)
    assert len(res["engine_ms_fused_reps"]) == 1      # pairs = reps = 1
    assert len(res["engine_ms_two_pass_reps"]) == 1
    assert "fused A/B" in buf.getvalue()


def test_run_config_fused_ab_vacuous_marker(tiny_cfg, tmp_path):
    """A config that never takes the fused path (tiny_cfg: no pallas —
    both arms run identical code) must record the explicit
    ``fused_ab_vacuous`` marker and WITHHOLD the timing series: an
    identical-code pair must not become a gated ledger series."""
    buf = io.StringIO()
    res = run_config(1, base_dir=str(tmp_path), out=buf,
                     env=_scrubbed_env(), fused_ab=True)
    assert res["checksums_match"], buf.getvalue()
    assert res.get("fused_ab_vacuous") is True, res
    assert "fused_ab_unavailable" in res
    assert "engine_ms_fused" not in res
