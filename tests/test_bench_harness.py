"""Bench harness: caching, checksum diff, compare_times format."""

import io
import os

import pytest

from dmlp_tpu.bench.configs import BenchConfig
from dmlp_tpu.bench.harness import (compare_times, ensure_input,
                                    ensure_oracle, run_config)


@pytest.fixture()
def tiny_cfg(monkeypatch):
    cfg = BenchConfig(1, 200, 20, 4, 0.0, 10.0, 1, 8, 4, 7, "tiny.in")
    monkeypatch.setitem(
        __import__("dmlp_tpu.bench.configs",
                   fromlist=["BENCH_CONFIGS"]).BENCH_CONFIGS, 1, cfg)
    return cfg


def test_input_generation_cached(tiny_cfg, tmp_path):
    d = str(tmp_path / "inputs")
    p1 = ensure_input(tiny_cfg, d)
    mtime = os.path.getmtime(p1)
    p2 = ensure_input(tiny_cfg, d)
    assert p1 == p2 and os.path.getmtime(p2) == mtime  # not regenerated
    with open(p1) as f:
        assert f.readline().strip() == "200 20 4"


def test_oracle_cached(tiny_cfg, tmp_path):
    inp = ensure_input(tiny_cfg, str(tmp_path / "inputs"))
    buf = io.StringIO()
    out1 = ensure_oracle(tiny_cfg, inp, str(tmp_path / "outputs"), buf)
    assert "cache" not in buf.getvalue()
    out2 = ensure_oracle(tiny_cfg, inp, str(tmp_path / "outputs"), buf)
    assert out1 == out2
    assert "Output found in cache. Skipping...\n" in buf.getvalue()


def test_run_config_end_to_end(tiny_cfg, tmp_path):
    buf = io.StringIO()
    res = run_config(1, base_dir=str(tmp_path), out=buf)
    assert res["checksums_match"], buf.getvalue()
    assert res["oracle_ms"] is not None and res["engine_ms"] is not None
    text = buf.getvalue()
    assert "Config 1: checksums PASS" in text
    assert "=== Performance Comparison ===" in text


def test_run_config_exact_mode(tiny_cfg, tmp_path):
    res = run_config(1, base_dir=str(tmp_path), fast=False,
                     out=io.StringIO())
    assert res["checksums_match"]


def test_compare_times_report_format():
    out = io.StringIO()
    pct = compare_times("Time taken: 100 ms\n", "Time taken: 80 ms\n", out)
    assert pct == pytest.approx(-20.0)
    assert "Benchmark time: 100 ms" in out.getvalue()
    assert "Engine time:    80 ms" in out.getvalue()
    assert "-20 ms (20.00% faster)" in out.getvalue()

    out = io.StringIO()
    pct = compare_times("Time taken: 80 ms\n", "Time taken: 100 ms\n", out)
    assert pct == pytest.approx(25.0)
    assert "+20 ms (25.00% slower)" in out.getvalue()

    out = io.StringIO()
    assert compare_times("nope\n", "Time taken: 1 ms\n", out) is None
    assert "Could not extract timing" in out.getvalue()
