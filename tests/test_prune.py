"""Pruned two-stage solve (ops.summaries): BYTE-IDENTITY is the contract.

Bound soundness at the unit level (every block bound dominates the f64
distances it claims to), then the adversarial engine-level contract:
corpora with duplicate rows astride summary-block boundaries and blocks
sitting exactly at the pruning threshold, solved with pruning on and
off × the fused gate on and off, at the single / sharded / ring / serve
levels — every arm byte-identical to the float64 golden model. Plus
non-vacuity (a norm-banded corpus must actually prune), the kill
switch, the ladder's ``prune`` rung, and the serving ingest
summary-invalidation fix (stale summaries are silent unsoundness).
"""

from __future__ import annotations

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.io.report import format_results
from dmlp_tpu.ops import summaries as osum


def _case(seed: int, n=2048, nq=12, na=5, kmax=16, block=256,
          banded=False, dup_boundaries=False):
    """Fuzz corpus: optional norm bands per block, optional duplicate
    rows straddling every summary-block boundary (the tie-adversarial
    case: a pruned block may not swallow one copy of a duplicate whose
    other copy survives — ids break the tie)."""
    rng = np.random.default_rng(seed)
    data = rng.uniform(0, 5, (n, na))
    if banded:
        for b in range(n // block):
            data[b * block:(b + 1) * block] += 40.0 * b
    if dup_boundaries:
        for b in range(1, n // block):
            edge = b * block
            data[edge] = data[edge - 1]          # exact duplicate pair
            if edge + 1 < n:
                data[edge + 1] = data[edge - 2]  # crossed duplicate
    labels = rng.integers(0, 6, n).astype(np.int32)
    ks = rng.integers(1, kmax + 1, nq).astype(np.int32)
    q = rng.uniform(0, 5, (nq, na))
    if banded:
        # one query per far band too, so pruning decisions interact
        q[-1] = data[n - block // 2] + rng.uniform(-0.5, 0.5, na)
    return KNNInput(Params(n, nq, na), labels, data, ks, q)


# -- unit: bound soundness ----------------------------------------------------

@pytest.mark.parametrize("seed", [1, 2, 3])
def test_block_bounds_dominate_true_distances(seed):
    inp = _case(seed, n=1024, nq=8, block=128, banded=(seed % 2 == 0))
    ranges = [(b * 128, (b + 1) * 128) for b in range(8)]
    summ = osum.build_summaries(inp.data_attrs, ranges)
    lb, ub = osum.block_bounds(inp.query_attrs, summ)
    d = np.square(inp.query_attrs[:, None, :]
                  - inp.data_attrs[None, :, :]).sum(-1)     # (Q, N) f64
    for b, (lo, hi) in enumerate(ranges):
        blockd = d[:, lo:hi]
        assert (lb[:, b] <= blockd.min(axis=1) + 1e-9).all()
        assert (ub[:, b] >= blockd.max(axis=1) - 1e-9).all()


def test_kth_thresholds_dominate_true_kth():
    inp = _case(4, n=1024, nq=16, block=128, banded=True)
    summ = osum.build_summaries(
        inp.data_attrs, [(b * 128, (b + 1) * 128) for b in range(8)])
    _, ub = osum.block_bounds(inp.query_attrs, summ)
    thr = osum.kth_thresholds(ub, summ.counts, inp.ks)
    d = np.sort(np.square(inp.query_attrs[:, None, :]
                          - inp.data_attrs[None, :, :]).sum(-1), axis=1)
    true_kth = d[np.arange(len(inp.ks)), inp.ks - 1]
    assert (thr >= true_kth - 1e-9).all()


@pytest.mark.parametrize("seed", [11, 12, 13, 14])
def test_prune_mask_never_drops_a_topk_block(seed):
    inp = _case(seed, n=2048, nq=10, block=256, banded=(seed % 2 == 0),
                dup_boundaries=True)
    summ = osum.build_summaries(
        inp.data_attrs, [(b * 256, (b + 1) * 256) for b in range(8)])
    keep, stats = osum.prune_mask(inp.query_attrs, inp.ks, summ)
    d = np.square(inp.query_attrs[:, None, :]
                  - inp.data_attrs[None, :, :]).sum(-1)
    for qi, k in enumerate(np.asarray(inp.ks)):
        topk_rows = np.argsort(d[qi], kind="stable")[:k]
        blocks = set(int(r) // 256 for r in topk_rows)
        assert all(keep[b] for b in blocks), (seed, qi, stats)


def test_empty_and_overflow_blocks():
    # corpus smaller than k: threshold must be +inf, nothing pruned
    inp = _case(5, n=64, nq=4, block=32, kmax=16)
    inp = KNNInput(inp.params, inp.labels, inp.data_attrs,
                   np.full(4, 64, np.int32), inp.query_attrs)
    summ = osum.build_summaries(inp.data_attrs, [(0, 32), (32, 64),
                                                 (64, 96)])
    assert summ.counts[2] == 0
    keep, _ = osum.prune_mask(inp.query_attrs, inp.ks, summ)
    assert keep[0] and keep[1] and not keep[2]  # empty never survives


# -- engine level: the byte-identity fuzz ------------------------------------

@pytest.mark.parametrize("seed,banded", [(21, True), (22, False),
                                         (23, True)])
def test_single_streaming_prune_on_off_byte_identical(monkeypatch, seed,
                                                      banded):
    inp = _case(seed, banded=banded, dup_boundaries=True)
    gold = format_results(knn_golden(inp))
    for prune in ("1", "0"):
        monkeypatch.setenv("DMLP_TPU_PRUNE", prune)
        eng = SingleChipEngine(EngineConfig(select="topk",
                                            data_block=256))
        assert format_results(eng.run(inp)) == gold, (seed, prune)
        if prune == "0":
            assert eng.last_prune["blocks_pruned"] == 0
        assert eng.last_prune["scanned_bytes"] <= \
            eng.last_prune["dense_bytes"]
    monkeypatch.delenv("DMLP_TPU_PRUNE")


def test_single_extract_prune_fused_matrix(monkeypatch):
    """The flagship path: 2 extract chunks, far band in chunk 2, prune
    on/off x fused gate on/off — all four arms byte-identical to
    golden, and the pruned arms must actually skip the far chunk."""
    rng = np.random.default_rng(31)
    n, nq, na = 14000, 6, 3
    data = rng.uniform(0, 1, (n, na))
    data[12800:] += 200.0
    # exact-duplicate pair INSIDE the to-be-pruned block (a tie group
    # the pruned scan must drop or keep as a unit); a duplicate pair
    # ACROSS the band boundary legitimately un-prunes — one copy would
    # be a near row inside the far block, or a far outlier inflating
    # the near block's box and hence the threshold (that arm is the
    # streaming fuzz's job, where byte identity is still asserted).
    data[12900] = data[12901]
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 4, n).astype(np.int32), data,
                   rng.integers(1, 6, nq).astype(np.int32),
                   rng.uniform(0, 1, (nq, na)))
    gold = format_results(knn_golden(inp))
    for fused in ("1", "0"):
        for prune in ("1", "0"):
            monkeypatch.setenv("DMLP_TPU_FUSED", fused)
            monkeypatch.setenv("DMLP_TPU_PRUNE", prune)
            eng = SingleChipEngine(EngineConfig(
                select="extract", use_pallas=True, data_block=12800))
            assert format_results(eng.run(inp)) == gold, (fused, prune)
            want = 1 if prune == "1" else 0
            assert eng.last_prune["blocks_pruned"] == want
    monkeypatch.delenv("DMLP_TPU_FUSED")
    monkeypatch.delenv("DMLP_TPU_PRUNE")


def test_nonvacuity_banded_corpus_prunes_most_blocks():
    """ISSUE acceptance: on a norm-banded corpus the pruned fraction
    must exceed 0.5 — near-band-0 queries can only need the first
    band(s)."""
    rng = np.random.default_rng(41)
    n, nq, na, block = 4096, 8, 6, 256
    data = rng.uniform(0, 2, (n, na))
    for b in range(n // block):
        data[b * block:(b + 1) * block] += 30.0 * b
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 5, n).astype(np.int32), data,
                   rng.integers(1, 9, nq).astype(np.int32),
                   rng.uniform(0, 2, (nq, na)))
    eng = SingleChipEngine(EngineConfig(select="topk", data_block=block))
    res = format_results(eng.run(inp))
    assert res == format_results(knn_golden(inp))
    assert eng.last_prune["pruned_fraction"] > 0.5, eng.last_prune
    assert eng.last_prune["scanned_bytes"] < \
        0.5 * eng.last_prune["dense_bytes"]


def test_dense_paths_stay_dense(monkeypatch):
    """candidates() and run_device_full have no f64-repair backstop on
    their output orderings — they must never take the pruned path even
    with the switch on."""
    monkeypatch.setenv("DMLP_TPU_PRUNE", "1")
    inp = _case(51, banded=True)
    eng = SingleChipEngine(EngineConfig(select="topk", data_block=256))
    eng.candidates(inp)
    assert eng.last_prune["blocks_pruned"] == 0
    eng.run_device_full(inp)
    assert eng.last_prune["blocks_pruned"] == 0
    monkeypatch.delenv("DMLP_TPU_PRUNE")


def test_prune_rung_allows_fused_kernel():
    from dmlp_tpu.ops import pallas_fused
    _, impl = pallas_fused.resolve_topk_kernel(128, 12800, 8, 32,
                                               rung="prune")
    assert impl == "fused"


def test_oom_degrades_prune_to_fused_byte_identical():
    """Staging OOMs on the pruned solve walk the ladder's top rungs
    (lowp -> prune -> fused): two faults land on the dense fused rung
    and the answer is unchanged."""
    from dmlp_tpu.resilience import inject, stats
    from dmlp_tpu.resilience.inject import FaultEntry, FaultSchedule

    inp = _case(61, banded=True)
    gold = format_results(knn_golden(inp))
    stats.reset()
    inject.install(FaultSchedule(
        [FaultEntry("single.stage_put", "oom", times=2)]))
    try:
        eng = SingleChipEngine(EngineConfig(select="topk",
                                            data_block=256))
        got = format_results(eng.run(inp))
    finally:
        inject.uninstall()
    assert got == gold
    assert eng.last_degrade_rung == "fused"
    degs = stats.snapshot()["degradations"]
    assert "lowp->prune" in degs and "prune->fused" in degs
    assert eng.last_prune["blocks_pruned"] == 0   # the fused rung is dense


# -- mesh engines -------------------------------------------------------------

def _mesh_case(seed=71):
    rng = np.random.default_rng(seed)
    n, nq, na = 25600, 8, 3
    data = rng.uniform(0, 1, (n, na))
    data[12800:] += 200.0
    data[12900] = data[12901]   # duplicate tie pair inside the far shard
    return KNNInput(Params(n, nq, na),
                    rng.integers(0, 4, n).astype(np.int32), data,
                    rng.integers(1, 6, nq).astype(np.int32),
                    rng.uniform(0, 1, (nq, na)))


@pytest.mark.parametrize("mode", ["sharded", "ring"])
def test_mesh_prune_on_off_byte_identical(monkeypatch, mode):
    """Each shard prunes locally before its fold: shard 1's far band
    folds dead (live mask), and the merged result is byte-identical to
    golden with pruning on and off."""
    from dmlp_tpu.engine.ring import RingEngine
    from dmlp_tpu.engine.sharded import ShardedEngine

    cls = RingEngine if mode == "ring" else ShardedEngine
    inp = _mesh_case()
    gold = format_results(knn_golden(inp))
    for prune in ("1", "0"):
        monkeypatch.setenv("DMLP_TPU_PRUNE", prune)
        eng = cls(EngineConfig(mode=mode, select="extract",
                               use_pallas=True, mesh_shape=(4, 2),
                               data_block=12800))
        assert format_results(eng.run(inp)) == gold, (mode, prune)
        want = 1 if prune == "1" else 0
        assert eng.last_prune["blocks_pruned"] == want, eng.last_prune
    monkeypatch.delenv("DMLP_TPU_PRUNE")


# -- serving ------------------------------------------------------------------

def _serve_fixture():
    rng = np.random.default_rng(81)
    n, na = 13000, 3
    data = rng.uniform(0, 1, (n, na))
    data[12800:] += 300.0          # block 1's 200 rows: far
    corpus = KNNInput(Params(n, 0, na),
                      rng.integers(0, 4, n).astype(np.int32), data,
                      np.zeros(0, np.int32), np.zeros((0, na)))
    from dmlp_tpu.serve.engine import ResidentEngine
    eng = ResidentEngine(corpus, EngineConfig(
        select="extract", use_pallas=True, data_block=12800))
    q = rng.uniform(0, 1, (6, na))
    ks = np.array([3, 1, 5, 2, 4, 3], np.int32)
    return eng, q, ks, rng


def _serve_golden(eng, q, ks):
    nrows = eng.n_real
    inp = KNNInput(Params(nrows, len(ks), eng.num_attrs),
                   eng._host_labels[:nrows].copy(),
                   eng._host_attrs[:nrows].copy(),
                   np.asarray(ks, np.int32), np.asarray(q, np.float64))
    return format_results(knn_golden(inp))


def test_serve_resident_prune_golden_identity():
    eng, q, ks, _ = _serve_fixture()
    got = format_results(eng.solve_batch(q, ks))
    assert got == _serve_golden(eng, q, ks)
    assert eng.last_prune["blocks_pruned"] == 1, eng.last_prune
    assert eng.bucket_stats()["last_prune_fraction"] == 0.5


def test_serve_ingest_rebuilds_summaries_and_unprunes():
    """The fix-with-test satellite: ingested rows that belong in a
    previously-pruned block must rebuild exactly that block's summary
    (counter asserted) and un-prune it — with a stale summary the new
    rows would silently vanish from every top-k."""
    eng, q, ks, rng = _serve_fixture()
    assert format_results(eng.solve_batch(q, ks)) == \
        _serve_golden(eng, q, ks)
    assert eng.last_prune["blocks_pruned"] == 1
    r0 = eng.summary_rebuilds
    new_rows = rng.uniform(0, 1, (20, eng.num_attrs))  # near the queries
    eng.ingest(rng.integers(0, 4, 20).astype(np.int32), new_rows)
    assert eng.summary_rebuilds == r0 + 1        # exactly block 1
    got = format_results(eng.solve_batch(q, ks))
    assert got == _serve_golden(eng, q, ks)      # ingested rows found
    assert eng.last_prune["blocks_pruned"] == 0  # block 1 un-pruned


def test_serve_prune_kill_switch(monkeypatch):
    monkeypatch.setenv("DMLP_TPU_PRUNE", "0")
    eng, q, ks, _ = _serve_fixture()
    assert format_results(eng.solve_batch(q, ks)) == \
        _serve_golden(eng, q, ks)
    assert eng.last_prune["blocks_pruned"] == 0
    monkeypatch.delenv("DMLP_TPU_PRUNE")


def test_split_lb_positive_fraction_on_uniform_corpus():
    """Non-vacuity of the 2-piece split on the hardest corpus for it:
    uniform data, where the whole-block boxes span the full cube and
    every whole-block lower bound is provably 0. The half-cube pieces
    must keep a strictly positive fraction of (query, live piece)
    lower bounds — the meter that shows the split buys real pruning
    information even when block-level pruning is hopeless."""
    inp = _case(61, n=2048, nq=16, na=6)
    ranges = [(i, i + 256) for i in range(0, 2048, 256)]
    summ = osum.build_summaries(inp.data_attrs, ranges)
    keep, stats = osum.prune_mask(inp.query_attrs, inp.ks, summ)
    assert keep.all()                       # uniform: nothing prunable
    assert stats["lb_positive_fraction"] > 0.0, stats
    # the whole-block-only format really is vacuous here — the split's
    # win is the difference
    flat = osum.build_summaries(inp.data_attrs, ranges, pieces=1)
    _, flat_stats = osum.prune_mask(inp.query_attrs, inp.ks, flat)
    assert "lb_positive_fraction" not in flat_stats
