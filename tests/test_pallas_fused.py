"""Fused distance→top-k megakernel (ops.pallas_fused) vs the two-pass
pipeline: BIT-IDENTITY is the contract.

The fused kernel's MXU tile gate may only elide blocks whose extraction
would have inserted nothing, so every output — dists, ids, the running
carry lists after warm folds — must equal the ungated kernel bit for
bit over the PR 3 tie-semantics fuzz corpus (duplicate rows astride
fused block boundaries included), with block skipping on AND off, in
interpret mode on CPU. Engine level: a DMLP_TPU_FUSED=1 run must be
byte-identical to a DMLP_TPU_FUSED=0 run and to the float64 golden
model, across the single-chip extract paths and the sharded mesh fold.
"""

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params
from tests.test_engine_single import assert_same_results
from tests.test_extract_fuzz import _case, _pad_stage


def _kernel_outputs(q, d, n_real, kc, *, mxu_gate, block_skip):
    """One fresh dispatch + one warm carry fold over shifted rows (the
    regime the gate actually optimizes) — returns every output."""
    from dmlp_tpu.ops.pallas_extract import extract_topk

    od1, oi1, it1 = extract_topk(q, d, n_real=n_real, kc=kc,
                                 interpret=True, tile_n=256,
                                 block_skip=block_skip, mxu_gate=mxu_gate)
    od2, oi2, it2 = extract_topk(q, d + 3.0, od1, oi1, n_real=n_real,
                                 id_base=n_real, kc=kc, interpret=True,
                                 tile_n=256, block_skip=block_skip,
                                 mxu_gate=mxu_gate)
    return [np.asarray(x) for x in (od1, oi1, od2, oi2)], \
        [np.asarray(x) for x in (it1, it2)]


@pytest.mark.parametrize("seed", [501, 502, 503, 504, 505, 506])
def test_fused_vs_two_pass_bit_identical_fuzz(seed):
    """Fuzz corpus (duplicate-heavy integer grids included), skip
    on/off x gate on/off: all four kernel configurations produce
    IDENTICAL dists/ids/carries — the gate and the skip are pure
    elisions."""
    inp = _case(seed)
    d, q, n_real, _ = _pad_stage(inp.data_attrs, inp.query_attrs)
    kc = 16
    outs = {}
    for gate in (False, True):
        for skip in (True, False):
            outs[(gate, skip)], _ = _kernel_outputs(
                q, d, n_real, kc, mxu_gate=gate, block_skip=skip)
    ref = outs[(False, True)]
    for key, got in outs.items():
        for a, b in zip(ref, got):
            assert np.array_equal(a, b), (seed, key)


def test_fused_tie_rows_astride_fused_block_boundary():
    """Duplicated rows exactly astride the fused kernel's in-kernel
    block boundary (tile_n=256: rows 255/256) and astride the carry
    fold: the MXU gate must not disturb the lowest-global-position tie
    contract. k=1 semantics checked through the composite sort."""
    import jax.numpy as jnp

    from dmlp_tpu.ops.pallas_extract import extract_topk

    rng = np.random.default_rng(29)
    na = 4
    # continuous values: only the DELIBERATE twins can tie at dist 0
    base = rng.uniform(-20, 20, (512, na))
    base[256] = base[255]                  # twins astride the boundary
    q2 = base[255][None, :]
    dd, qq, _, _ = _pad_stage(base, q2)
    for gate in (True, False):
        od, oi, _ = extract_topk(qq, dd, n_real=512, kc=8,
                                 interpret=True, tile_n=256,
                                 mxu_gate=gate)
        oi_np = np.asarray(oi)[0]
        srt = oi_np[np.argsort(np.asarray(od)[0], kind="stable")]
        assert {255, 256} <= set(oi_np.tolist())
        assert min(srt[0], srt[1]) == 255

    # chunk/carry form: the twin arrives in a LATER fold with higher
    # global ids — it must tie into the list without displacing id 255
    d1, d2 = base[:256], base[256:]
    dd1, qq, _, _ = _pad_stage(d1, q2)
    dd2 = jnp.asarray(np.asarray(_pad_stage(d2, q2)[0]))
    for gate in (True, False):
        od, oi, _ = extract_topk(qq, dd1, n_real=256, kc=8,
                                 interpret=True, tile_n=256,
                                 mxu_gate=gate)
        od, oi, _ = extract_topk(qq, dd2, od, oi, n_real=256,
                                 id_base=256, kc=8, interpret=True,
                                 tile_n=256, mxu_gate=gate)
        oi_np = np.asarray(oi)[0]
        srt = oi_np[np.argsort(np.asarray(od)[0], kind="stable")]
        assert {255, 256} <= set(oi_np.tolist())
        assert min(srt[0], srt[1]) == 255


def test_mxu_gate_skips_hopeless_blocks_outright():
    """The gate's whole point: a warm fold whose every candidate is
    provably worse than the current k-th best must cost ZERO loop
    iterations even with the r6 block-skip prefilter DISABLED — the
    norm bound gates the while-loop (and, on hardware, the matmul)
    before the prefilter ever runs. Outputs stay bit-identical."""
    import jax.numpy as jnp

    from dmlp_tpu.ops.pallas_extract import extract_topk

    rng = np.random.default_rng(3)
    n, nq, a, kc = 512, 8, 6, 16
    d = jnp.asarray(rng.uniform(0, 10, (n, a)), jnp.float32)
    q = jnp.asarray(rng.uniform(0, 10, (nq, a)), jnp.float32)
    d_far = d + 1000.0                    # norm gap >> any current best
    res = {}
    for gate in (True, False):
        od1, oi1, _ = extract_topk(q, d, n_real=n, kc=kc, interpret=True,
                                   block_skip=False, mxu_gate=gate)
        od2, oi2, it2 = extract_topk(q, d_far, od1, oi1, n_real=n,
                                     id_base=n, kc=kc, interpret=True,
                                     block_skip=False, mxu_gate=gate)
        res[gate] = (np.asarray(od2), np.asarray(oi2),
                     int(np.asarray(it2).sum()))
    assert np.array_equal(res[True][0], res[False][0])
    assert np.array_equal(res[True][1], res[False][1])
    assert res[True][2] == 0              # gated: zero loop iterations
    assert res[False][2] > 0              # ungated pays full discovery


# -- selection / kill switch -------------------------------------------------

def test_resolve_topk_kernel_prefers_fused_and_honors_kill_switch(
        monkeypatch):
    from dmlp_tpu.ops import pallas_fused
    from dmlp_tpu.ops.pallas_extract import extract_topk

    kern, impl = pallas_fused.resolve_topk_kernel(128, 12800, 8, 32)
    assert impl == "fused" and kern is pallas_fused.fused_topk

    monkeypatch.setenv("DMLP_TPU_FUSED", "0")
    kern, impl = pallas_fused.resolve_topk_kernel(128, 12800, 8, 32)
    assert impl == "extract" and kern is extract_topk

    monkeypatch.delenv("DMLP_TPU_FUSED")
    kern, impl = pallas_fused.resolve_topk_kernel(128, 12800, 8, 32)
    assert impl == "fused"


def test_resolve_topk_kernel_degrade_rung_pins_two_pass():
    """Any rung below "fused" (the resilience ladder's first step-down)
    must dispatch the two-pass kernel even with the switch on."""
    from dmlp_tpu.ops import pallas_fused

    for rung in ("tuned", "heuristic"):
        _, impl = pallas_fused.resolve_topk_kernel(128, 12800, 8, 32,
                                                   rung=rung)
        assert impl == "extract", rung


def test_resolve_topk_kernel_unsupported_shape_falls_through():
    from dmlp_tpu.ops import pallas_fused

    # kc beyond the kernel cap: neither kernel tiles it
    kern, impl = pallas_fused.resolve_topk_kernel(128, 12800, 8, 4096)
    assert kern is None and impl is None


# -- engine level ------------------------------------------------------------

def _engine_case(seed=41, n=900, nq=12, na=4):
    rng = np.random.default_rng(seed)
    return KNNInput(Params(n, nq, na),
                    rng.integers(0, 5, n).astype(np.int32),
                    rng.uniform(-20, 20, (n, na)),
                    rng.integers(1, 28, nq).astype(np.int32),
                    rng.uniform(-20, 20, (nq, na)))


def test_engine_fused_on_off_byte_identical_and_golden(monkeypatch):
    from dmlp_tpu.io.report import format_results

    inp = _engine_case()
    results = {}
    for fused in ("1", "0"):
        monkeypatch.setenv("DMLP_TPU_FUSED", fused)
        eng = SingleChipEngine(EngineConfig(select="extract",
                                            use_pallas=True))
        results[fused] = (format_results(eng.run(inp)),
                          eng.last_extract_impl)
    assert results["1"][0] == results["0"][0]          # byte identical
    assert results["1"][1] == "fused"
    assert results["0"][1] == "extract"
    monkeypatch.delenv("DMLP_TPU_FUSED")
    assert_same_results(
        SingleChipEngine(EngineConfig(select="extract",
                                      use_pallas=True)).run(inp),
        knn_golden(inp), check_dists=False)


def test_engine_multipass_fused_on_off_byte_identical(monkeypatch):
    """The multipass extract path (floor-masked resident passes) under
    the fused kernel: same bytes as two-pass, and the engine reports
    the impl it dispatched."""
    from dmlp_tpu.io.report import format_results

    rng = np.random.default_rng(17)
    n, nq, na = 600, 6, 3
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 4, n).astype(np.int32),
                   rng.uniform(-10, 10, (n, na)),
                   np.full(nq, 500, np.int32),    # wide k: multipass
                   rng.uniform(-10, 10, (nq, na)))
    outs = {}
    for fused in ("1", "0"):
        monkeypatch.setenv("DMLP_TPU_FUSED", fused)
        eng = SingleChipEngine(EngineConfig(select="extract",
                                            use_pallas=True))
        outs[fused] = format_results(eng.run(inp))
    assert outs["1"] == outs["0"]


def test_sharded_engine_fused_on_off_byte_identical(monkeypatch):
    """The mesh chunk-fold path bakes the fused/two-pass choice into its
    compiled-program cache key: flipping the switch recompiles the
    other program and the outputs stay byte-identical."""
    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.io.report import format_results

    inp = _engine_case(seed=43, n=1200, nq=16, na=4)
    outs = {}
    for fused in ("1", "0"):
        monkeypatch.setenv("DMLP_TPU_FUSED", fused)
        eng = ShardedEngine(EngineConfig(select="extract",
                                         use_pallas=True))
        outs[fused] = (format_results(eng.run(inp)),
                       eng.last_extract_impl)
    assert outs["1"][0] == outs["0"][0]
    assert outs["1"][1] == "fused" and outs["0"][1] == "extract"
    assert_same_results(
        ShardedEngine(EngineConfig(select="extract",
                                   use_pallas=True)).run(inp),
        knn_golden(inp), check_dists=False)


def test_fused_rung_degrades_to_two_pass_on_oom(monkeypatch, tmp_path):
    """Resilience integration: a fused-path OOM steps the ladder down
    to the tuned two-pass kernel (one rung, not a crash), the degrade
    event lands in the resilience stats block, and the output is
    byte-identical to the unfaulted run."""
    import json

    from dmlp_tpu.resilience import inject, stats
    from dmlp_tpu.io.report import format_results

    inp = _engine_case(seed=47)
    golden = format_results(
        SingleChipEngine(EngineConfig(select="extract",
                                      use_pallas=True)).run(inp))

    sched = {"schema": 1, "seed": 5, "faults": [
        {"site": "single.stage_put", "kind": "oom", "times": 3}]}
    p = tmp_path / "faults.json"
    p.write_text(json.dumps(sched))
    monkeypatch.setenv("DMLP_TPU_FAULTS", str(p))
    stats.reset()
    inject.install_from_env()
    try:
        eng = SingleChipEngine(EngineConfig(select="extract",
                                            use_pallas=True))
        got = format_results(eng.run(inp))
    finally:
        inject.uninstall()
        monkeypatch.delenv("DMLP_TPU_FAULTS")
    assert got == golden
    assert eng.last_degrade_rung == "tuned"
    assert eng.last_extract_impl == "extract"
    snap = stats.snapshot()["degradations"]
    assert "lowp->prune" in snap and "prune->fused" in snap \
        and "fused->tuned" in snap


# -- analytic cost model -----------------------------------------------------

def test_fused_cost_model_shows_hbm_traffic_elimination():
    """The acceptance number: on the ROOFLINE_r05 shape the fused
    dispatch's HBM bytes drop by exactly the (nq, nd) f32 distance
    write+read the two-pass pipeline pays — ~2x hot-path traffic."""
    from dmlp_tpu.obs.kernel_cost import (fused_topk_cost,
                                          two_pass_equivalent_cost)

    qb, b, a, kc = 10240, 204800, 64, 40   # ROOFLINE_r05 dispatch shape
    fused = fused_topk_cost(qb, b, a, kc)
    two = two_pass_equivalent_cost(qb, b, a, kc)
    dist_rt = 2.0 * 4.0 * qb * b           # f32 write + re-read
    assert two["bytes_accessed"] - fused["bytes_accessed"] \
        == pytest.approx(dist_rt)
    assert fused["hbm_bytes_saved_vs_two_pass"] == pytest.approx(dist_rt)
    assert fused["hbm_traffic_reduction_x"] >= 1.9
    assert fused["extraction_term"] == "modeled_lower_bound"
    meas = fused_topk_cost(qb, b, a, kc, iters_total=1000)
    assert meas["extraction_term"] == "measured"
    assert meas["flops"] > fused["flops"]


def test_fused_dispatch_resolves_analytic_model():
    """obs.counters must resolve fused_topk through the analytic table
    (pallas_call has no XLA cost analysis) — the R106 runtime half."""
    import jax.numpy as jnp

    from dmlp_tpu.obs.kernel_cost import analytic_cost
    from dmlp_tpu.ops.pallas_fused import fused_topk

    q = jnp.zeros((16, 8), jnp.float32)
    d = jnp.zeros((256, 8), jnp.float32)
    out = analytic_cost(fused_topk, (q, d), {"kc": 16})
    assert out is not None and out["bytes_accessed"] > 0
    assert out["hbm_traffic_reduction_x"] > 1.0
