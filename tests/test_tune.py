"""The measured autotuner (dmlp_tpu.tune): cache round-trip, shape-bucket
keying, heuristic fallback (absent cache / foreign device kind), and
alignment rejection — plus the hot-path integration: pallas_extract
resolves variants through the cache, and an uncached process is
bit-identical to the pre-tuner heuristics.

Every test isolates the cache via $DMLP_TPU_TUNE_CACHE (monkeypatch) and
clears the per-process lookup memo on both sides — the suite must never
read or write a developer's real ~/.cache file.
"""

import json
import os

import numpy as np
import pytest

from dmlp_tpu.tune import (VariantCache, cache_path, clear_lookup_memo,
                           lookup_variant, shape_bucket)
from dmlp_tpu.tune.cache import validate_variant, variant_fits


@pytest.fixture
def tune_cache_path(tmp_path, monkeypatch):
    path = str(tmp_path / "variants.json")
    monkeypatch.setenv("DMLP_TPU_TUNE_CACHE", path)
    clear_lookup_memo()
    yield path
    clear_lookup_memo()


# ---------------------------------------------------------------------------
# cache round-trip + keying
# ---------------------------------------------------------------------------

def test_cache_roundtrip_write_reload_hit(tune_cache_path):
    cache = VariantCache()
    v = {"tile_q": 64, "tile_n": 6144, "ne": 4, "unroll": 1}
    cache.put("TPU v5 lite", 51200, 40, v, a=64, measured_ms=12.3,
              swept=17, shape=(10240, 51200, 64))
    saved = cache.save(tune_cache_path)
    assert saved == tune_cache_path

    reloaded = VariantCache.load(tune_cache_path)
    assert reloaded.get("TPU v5 lite", 51200, 40, a=64) == v
    # and through the memoized hot-path read, with explicit device kind
    assert lookup_variant(40, 51200, a=64,
                          device_kind="TPU v5 lite") == v


def test_cache_file_is_schema_validated(tune_cache_path):
    VariantCache().save(tune_cache_path)
    doc = json.load(open(tune_cache_path))
    assert doc["schema"] == 3
    assert doc["kernel"] == "pallas_topk"
    VariantCache.validate_doc(doc)  # round-trips its own schema

    doc["schema"] = 99
    with pytest.raises(ValueError):
        VariantCache.validate_doc(doc)
    with pytest.raises(ValueError):
        VariantCache.validate_doc({"schema": 1, "kernel": "extract_topk",
                                   "entries": {"k": {"variant":
                                                     {"tile_q": 7}}}})
    # schema-2 entry keys must carry a known kernel namespace
    with pytest.raises(ValueError):
        VariantCache.validate_doc(
            {"schema": 2, "kernel": "pallas_topk",
             "entries": {"cpu|b16384|a8|kc16|float32":
                         {"variant": {"tile_q": 64, "ne": 2,
                                      "unroll": 1}}}})


def test_schema1_cache_loads_leniently_into_extract_namespace(
        tune_cache_path):
    """A pre-fused (schema-1, extract-only) cache file still loads: its
    keys upgrade to the extract_topk namespace in memory, so a tuned
    machine keeps its winners across the schema bump — and the fused
    namespace stays empty (never inherits extract winners)."""
    v = {"tile_q": 64, "ne": 4, "unroll": 1}
    with open(tune_cache_path, "w") as f:
        json.dump({"schema": 1, "kernel": "extract_topk",
                   "entries": {"cpu|b16384|a8|kc16|float32":
                               {"variant": v}}}, f)
    VariantCache.validate_doc(json.load(open(tune_cache_path)))
    clear_lookup_memo()
    assert lookup_variant(16, 12800, a=8, device_kind="cpu") == v
    assert lookup_variant(16, 12800, a=8, device_kind="cpu",
                          kernel="fused_topk") is None


def test_fused_namespace_is_keyed_separately(tune_cache_path):
    """Winners cached under kernel="fused_topk" resolve only through the
    fused lookup; the extract namespace at the same (device, b, a, kc)
    key is independent."""
    vf = {"tile_q": 32, "tile_n": 256, "ne": 2, "unroll": 1}
    ve = {"tile_q": 64, "ne": 4, "unroll": 1}
    cache = VariantCache()
    cache.put("cpu", 12800, 16, vf, a=8, kernel="fused_topk")
    cache.put("cpu", 12800, 16, ve, a=8)
    cache.save(tune_cache_path)
    clear_lookup_memo()
    assert lookup_variant(16, 12800, a=8, device_kind="cpu",
                          kernel="fused_topk") == vf
    assert lookup_variant(16, 12800, a=8, device_kind="cpu") == ve
    with pytest.raises(ValueError):
        cache.put("cpu", 12800, 16, ve, a=8, kernel="mystery_kernel")


def test_put_rejects_invalid_variants():
    cache = VariantCache()
    for bad in ({"tile_q": 7, "ne": 2, "unroll": 1},      # tq not mult 8
                {"tile_q": 64, "ne": 3, "unroll": 1},     # illegal ne
                {"tile_q": 64, "ne": 2, "unroll": 0},     # unroll < 1
                {"tile_q": 64, "ne": 4, "unroll": 1,
                 "tile_n": 640}):                         # tn % 512 != 0
        assert not validate_variant(bad)
        with pytest.raises(ValueError):
            cache.put("cpu", 1024, 16, bad, a=8)


def test_shape_bucket_keying(tune_cache_path):
    assert shape_bucket(12800) == shape_bucket(16000) == 16384
    assert shape_bucket(51200) == 65536
    cache = VariantCache()
    v = {"tile_q": 128, "ne": 2, "unroll": 1}
    cache.put("cpu", 12800, 16, v, a=8)
    cache.save(tune_cache_path)
    # same b and a buckets: hit for a DIFFERENT (256-aligned) row count
    assert lookup_variant(16, 16128, a=8, device_kind="cpu") == v
    # different b bucket: miss
    assert lookup_variant(16, 51200, a=8, device_kind="cpu") is None
    # different kc: miss
    assert lookup_variant(24, 12800, a=8, device_kind="cpu") is None
    # different a bucket (VMEM regime): miss
    assert lookup_variant(16, 12800, a=64, device_kind="cpu") is None
    # unknown a never matches (every real dispatch site passes it)
    assert lookup_variant(16, 12800, device_kind="cpu") is None


# ---------------------------------------------------------------------------
# fallback-to-heuristic
# ---------------------------------------------------------------------------

def test_lookup_absent_cache_is_none_and_resolution_matches_heuristic(
        tmp_path, monkeypatch):
    monkeypatch.setenv("DMLP_TPU_TUNE_CACHE",
                       str(tmp_path / "does-not-exist.json"))
    clear_lookup_memo()
    try:
        assert lookup_variant(40, 51200, a=64) is None
        from dmlp_tpu.ops.pallas_extract import (_resolve_variant,
                                                 tuned_variant)
        # bit-identical to the pre-tuner heuristics, both regimes
        assert _resolve_variant(40, 51200) == tuned_variant(40)
        assert _resolve_variant(136, 51200) == tuned_variant(136)
        # and the heuristic's own ne-alignment fallback still applies
        assert _resolve_variant(136, 128 * 2 * 7)["ne"] == 2
    finally:
        clear_lookup_memo()


def test_lookup_device_kind_mismatch_falls_back(tune_cache_path):
    cache = VariantCache()
    cache.put("TPU v5 lite", 12800, 16,
              {"tile_q": 64, "ne": 4, "unroll": 2}, a=8)
    cache.save(tune_cache_path)
    clear_lookup_memo()
    # the current backend is CPU (tier-1 env) — the TPU entry must not hit
    assert lookup_variant(16, 12800, a=8) is None
    from dmlp_tpu.ops.pallas_extract import _resolve_variant, tuned_variant
    assert _resolve_variant(16, 12800) == tuned_variant(16)


def test_lookup_unreadable_cache_is_none(tune_cache_path):
    with open(tune_cache_path, "w") as f:
        f.write("{not json")
    clear_lookup_memo()
    assert lookup_variant(16, 12800, a=8, device_kind="cpu") is None


# ---------------------------------------------------------------------------
# alignment rejection
# ---------------------------------------------------------------------------

def test_alignment_rejection_ne_cannot_tile_b(tune_cache_path):
    v4 = {"tile_q": 64, "ne": 4, "unroll": 1}
    cache = VariantCache()
    cache.put("cpu", 12800, 16, v4, a=8)
    cache.save(tune_cache_path)
    clear_lookup_memo()
    # 12800 % 512 == 0: fits
    assert lookup_variant(16, 12800, a=8, device_kind="cpu") == v4
    # 12544 = 128*98 (same bucket, % 512 != 0): the ne=4 entry cannot
    # tile it — lookup rejects, resolution falls back to the heuristic
    assert not variant_fits(v4, 12544, 16)
    assert lookup_variant(16, 12544, a=8, device_kind="cpu") is None
    from dmlp_tpu.ops.pallas_extract import _resolve_variant
    assert _resolve_variant(16, 12544)["ne"] == 2

    # kc wider than the entry's tile_n is a misfit too
    cache.put("cpu", 12800, 320,
              {"tile_q": 64, "tile_n": 256, "ne": 2, "unroll": 1}, a=8)
    cache.save(tune_cache_path)
    clear_lookup_memo()
    assert lookup_variant(320, 12800, a=8, device_kind="cpu") is None


# ---------------------------------------------------------------------------
# the sweep machinery + end-to-end resolution through a written cache
# ---------------------------------------------------------------------------

def test_variant_space_only_yields_supported_variants():
    from dmlp_tpu.ops.pallas_extract import variant_supports

    space = __import__("dmlp_tpu.tune.sweep",
                       fromlist=["variant_space"]).variant_space(
        128, 12800, 8, 16)
    assert space, "space must not be empty at a tileable shape"
    seen = set()
    for v in space:
        key = (v["tile_q"], v["tile_n"], v["ne"], v["unroll"])
        assert key not in seen       # no duplicates
        seen.add(key)
        assert validate_variant(v)
        assert variant_supports(128, 12800, 8, 16, v)
    # ne=8 cannot tile 12800 (12800 % 1024 != 0) — must be absent
    assert all(v["ne"] != 8 for v in space)


def test_time_variant_measures_interpret_kernel():
    import jax.numpy as jnp
    from dmlp_tpu.tune.sweep import time_variant_ms

    rng = np.random.default_rng(3)
    d = jnp.asarray(rng.uniform(0, 10, (1024, 4)), jnp.float32)
    q = jnp.asarray(rng.uniform(0, 10, (16, 4)), jnp.float32)
    ms = time_variant_ms(q, d, 1000, 8,
                         {"tile_q": 16, "tile_n": 256, "ne": 2,
                          "unroll": 1}, reps=1, interpret=True)
    assert ms > 0


def test_written_cache_drives_engine_resolution_and_parity(
        tune_cache_path):
    """End to end: a cache pinning a non-default variant (small tile_n →
    multiple in-kernel blocks) changes HOW the engine's kernel tiles but
    not WHAT it returns — golden parity with the tuned variant active,
    and the resolution visibly differs from the heuristic."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine, resolve_kcap
    from dmlp_tpu.golden.reference import knn_golden
    from dmlp_tpu.io.grammar import KNNInput, Params
    from dmlp_tpu.ops.pallas_extract import resolve_variant, tuned_variant
    from tests.test_engine_single import assert_same_results

    rng = np.random.default_rng(11)
    n, nq, na = 700, 9, 4
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 4, n).astype(np.int32),
                   rng.uniform(-20, 20, (n, na)),
                   rng.integers(1, 24, nq).astype(np.int32),
                   rng.uniform(-20, 20, (nq, na)))
    kc = resolve_kcap(EngineConfig(), int(inp.ks.max()), "extract",
                      1 << 30, staging="float32")
    pinned = {"tile_q": 32, "tile_n": 256, "ne": 2, "unroll": 1}
    cache = VariantCache()
    # engine dispatch: chunk_rows 12800, qpad 128 (QUERY_TILE), a = na.
    # The engine prefers the fused megakernel, which resolves through
    # the fused_topk namespace — pin BOTH so whichever kernel dispatches
    # sees the tuned tiles (and the span proves which one resolved).
    cache.put("cpu", 12800, kc, pinned, a=na)
    cache.put("cpu", 12800, kc, pinned, a=na, kernel="fused_topk")
    cache.save(tune_cache_path)
    clear_lookup_memo()

    assert resolve_variant(kc, 12800, 128, na) == pinned
    assert resolve_variant(kc, 12800, 128, na) != tuned_variant(kc)
    from dmlp_tpu.obs import trace as obs_trace
    tracer = obs_trace.install(obs_trace.Tracer())
    try:
        eng = SingleChipEngine(EngineConfig(select="extract",
                                            use_pallas=True))
        got = eng.run(inp)
    finally:
        obs_trace.uninstall()
    assert eng._last_select == "extract"
    # the span records the variant the dispatch RESOLVED (and, with the
    # resolution hoisted out of the jit, the one it actually compiled)
    spans = [e for e in tracer.to_dict()["traceEvents"]
             if e.get("name") == "single.enqueue_extract"]
    assert spans and spans[0]["args"]["variant"] == pinned
    assert spans[0]["args"]["impl"] == eng.last_extract_impl
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_tune_cli_validate(tune_cache_path, capsys):
    from dmlp_tpu.tune.__main__ import main

    VariantCache().save(tune_cache_path)
    assert main(["--validate", tune_cache_path]) == 0
    with open(tune_cache_path, "w") as f:
        json.dump({"schema": 0}, f)
    assert main(["--validate", tune_cache_path]) == 1


def test_default_cache_path_honors_env(monkeypatch):
    monkeypatch.setenv("DMLP_TPU_TUNE_CACHE", "/tmp/x.json")
    assert cache_path() == "/tmp/x.json"
    monkeypatch.delenv("DMLP_TPU_TUNE_CACHE")
    assert cache_path().endswith(
        os.path.join(".cache", "dmlp_tpu", "extract_variants.json"))
