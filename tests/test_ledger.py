"""Perf ledger + regression sentinel (obs.ledger, dmlp_tpu.report,
tools/perf_gate.py).

Fixture-driven ingestion over the REAL repo-root artifact population
(every schema present at the root must round-trip into the ledger
without silent drops), noise-aware comparison semantics (noise band /
insufficient_trials / device_mismatch), the report CLI, and the gate's
pass / fail / insufficient-data paths — including the acceptance
requirement that a synthetic regressed RunRecord round demonstrably
fails the gate.
"""

import importlib.util
import json
import os

import pytest

from dmlp_tpu.obs.ledger import (MIN_TRIALS, build_ledger, compare_points,
                                 discover_artifacts, ingest_file,
                                 noise_band, series_deltas)
from dmlp_tpu.obs.run import SCHEMA_VERSION, RunRecord

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# ingestion over the real repo-root artifacts — every schema present
# ---------------------------------------------------------------------------

def test_ledger_covers_every_root_artifact():
    files = discover_artifacts(REPO)
    assert len(files) >= 40, "artifact discovery lost the repo root"
    ledger = build_ledger(REPO)
    cov = ledger["coverage"]
    # one entry per file, none silently dropped
    assert cov["files"] == len(files)
    assert len(ledger["entries"]) == len(files)
    # the acceptance floor: >= 90% parsed, the rest EXPLICIT
    assert cov["fraction"] >= 0.9, cov["unparseable_sources"]
    for e in ledger["entries"]:
        assert e["status"] in ("parsed", "unparseable")
        if e["status"] == "unparseable":
            assert e["error"]          # named reason, never silence


def test_ledger_parses_each_known_family():
    ledger = build_ledger(REPO)
    fams = {e["family"] for e in ledger["entries"]
            if e["status"] == "parsed"}
    # the families the repo root actually holds today
    assert {"bench", "harness", "sweep", "trainbench", "roofline",
            "pipebench", "runrecord", "generic"} <= fams
    # harness series carry per-rep trials (the gate's raw material)
    pts = ledger["series"]["harness/config1/engine_ms"]
    assert any(p.get("trials") for p in pts)
    rounds = {p["round"] for p in pts}
    assert {3, 4, 5} <= rounds


def test_ledger_runrecord_round_trip(tmp_path):
    # schema RunRecords (single + jsonl), a legacy harness shape, and
    # junk — the ledger must parse the first three and explicitly mark
    # the junk, dropping nothing.
    RunRecord(kind="bench", tool="t", config={"config_id": 1},
              metrics={"engine_ms": 100,
                       "engine_ms_reps": [99, 100, 101],
                       "obs_overhead_pct": 1.5},
              device="cpu", round=6).write(str(tmp_path / "BENCH_r06.json"))
    rec = RunRecord(kind="train", tool="t2", metrics={"step_time_ms": 5.0},
                    round=6)
    rec.append_jsonl(str(tmp_path / "TRAINBENCH_r06.jsonl"))
    with open(tmp_path / "HARNESS_r05.json", "w") as f:
        json.dump({"configs": [{"config": 1, "engine_ms": 120,
                                "engine_ms_reps": [118, 120, 125]}]}, f)
    with open(tmp_path / "BENCH_r07.json", "w") as f:
        f.write("{not json")

    ledger = build_ledger(str(tmp_path))
    by_src = {e["source"]: e for e in ledger["entries"]}
    assert by_src["BENCH_r06.json"]["status"] == "parsed"
    assert by_src["BENCH_r06.json"]["family"] == "runrecord"
    assert by_src["TRAINBENCH_r06.jsonl"]["status"] == "parsed"
    assert by_src["HARNESS_r05.json"]["status"] == "parsed"
    assert by_src["BENCH_r07.json"]["status"] == "unparseable"
    # envelope round/device flow into the points; trials captured
    (pt,) = ledger["series"]["bench:t/config1/engine_ms"]
    assert pt["round"] == 6 and pt["device"] == "cpu"
    assert pt["trials"] == [99.0, 100.0, 101.0]
    # obs overhead is its own tracked series
    assert "bench:t/config1/obs_overhead_pct" in ledger["series"]


def test_runrecord_schema2_fields_roundtrip():
    rec = RunRecord(kind="bench", tool="x", round=6, device="TPU v5 lite")
    back = RunRecord.from_dict(json.loads(rec.to_json()))
    assert back.schema == SCHEMA_VERSION
    assert back.round == 6 and back.device == "TPU v5 lite"
    # a schema-1 record (no round/device) still loads
    old = RunRecord.from_dict({"kind": "bench", "tool": "x", "schema": 1})
    assert old.round is None and old.device is None


def test_unavailable_marker_record_is_parsed_not_dropped(tmp_path):
    # e.g. ROOFLINE_r06-style records whose metrics are all markers
    RunRecord(kind="roofline", tool="t",
              metrics={"roofline_unavailable": "no TPU"},
              round=6).write(str(tmp_path / "ROOFLINE_r06.json"))
    entry = ingest_file(str(tmp_path / "ROOFLINE_r06.json"))
    assert entry["status"] == "parsed"
    assert entry["points"] == []


# ---------------------------------------------------------------------------
# noise-aware comparison semantics
# ---------------------------------------------------------------------------

def _pt(value, trials=None, device="cpu", round_=1, better="lower"):
    return {"series": "s", "value": value, "trials": trials,
            "device": device, "round": round_, "better": better}


def test_compare_within_noise_is_not_significant():
    a = _pt(100, trials=[95, 100, 105], round_=1)
    b = _pt(102, trials=[97, 102, 106], round_=2)
    cmp = compare_points(a, b)
    assert "marker" not in cmp
    assert cmp["significant"] is False
    assert cmp["regressed"] is False


def test_compare_flags_regression_beyond_band():
    a = _pt(100, trials=[99, 100, 101], round_=1)
    b = _pt(200, trials=[198, 200, 202], round_=2)
    cmp = compare_points(a, b)
    assert cmp["significant"] and cmp["regressed"]
    # and the same magnitude in the good direction is an improvement
    cmp2 = compare_points(b, a)
    assert cmp2["improved"] and not cmp2["regressed"]


def test_compare_higher_is_better_direction():
    a = _pt(100, trials=[99, 100, 101], round_=1, better="higher")
    b = _pt(50, trials=[49, 50, 51], round_=2, better="higher")
    cmp = compare_points(a, b)
    assert cmp["regressed"]  # throughput halved


def test_compare_insufficient_trials_marker():
    a = _pt(100, trials=None, round_=1)
    b = _pt(500, trials=[499, 500, 501], round_=2)
    cmp = compare_points(a, b)
    assert cmp["marker"] == "insufficient_trials"
    assert "regressed" not in cmp           # never a silent verdict
    assert cmp["delta_pct"] == 400.0        # raw delta still reported
    short = compare_points(_pt(1, trials=[1] * (MIN_TRIALS - 1)),
                           _pt(9, trials=[9] * MIN_TRIALS))
    assert short["marker"] == "insufficient_trials"


def test_compare_device_mismatch_marker():
    cmp = compare_points(_pt(100, trials=[1, 2, 3], device="cpu"),
                         _pt(900, trials=[1, 2, 3], device="TPU v5 lite"))
    assert cmp["marker"] == "device_mismatch"
    assert "regressed" not in cmp


def test_noise_band_floor_absorbs_quantized_timers():
    # 3 near-identical ms-quantized trials: MAD ~ 0, but the band must
    # not collapse below the relative floor
    assert noise_band([1000, 1000, 1001]) >= 0.02 * 1000


# ---------------------------------------------------------------------------
# the report CLI and the gate
# ---------------------------------------------------------------------------

def test_report_cli_builds_ledger_and_enforces_coverage(tmp_path):
    import dmlp_tpu.report as report
    out = tmp_path / "LEDGER.json"
    md = tmp_path / "REPORT.md"
    rc = report.main(["--root", REPO, "--out", str(out), "--md", str(md),
                      "--min-coverage", "0.9"])
    assert rc == 0
    ledger = json.loads(out.read_text())
    assert ledger["ledger_schema"] == 1
    assert ledger["coverage"]["fraction"] >= 0.9
    text = md.read_text()
    assert "Round-over-round trajectories" in text
    assert "harness/config1/engine_ms" in text
    assert "pct_of_roof" in text        # the roofline section


def test_perf_gate_passes_on_current_tree(capsys):
    perf_gate = _load_tool("perf_gate")
    rc = perf_gate.main(["--root", REPO])
    assert rc == 0
    out = capsys.readouterr().out
    assert "gated series checked" in out


def _write_round(tmp_path, round_, reps):
    RunRecord(kind="bench", tool="dmlp_tpu.bench",
              config={"config_id": 1},
              metrics={"engine_ms": sorted(reps)[len(reps) // 2],
                       "engine_ms_reps": reps},
              device="cpu", round=round_).append_jsonl(
        str(tmp_path / f"BENCH_r{round_:02d}.jsonl"))


def test_perf_gate_fails_on_synthetic_regressed_runrecord(tmp_path):
    perf_gate = _load_tool("perf_gate")
    _write_round(tmp_path, 6, [100, 101, 99])
    _write_round(tmp_path, 7, [205, 200, 202])   # 2x slower, tight reps
    rc = perf_gate.main(["--root", str(tmp_path)])
    assert rc == 1
    res = perf_gate.run_gate(str(tmp_path))
    (reg,) = res["regressions"]
    assert reg["series"].endswith("config1/engine_ms")
    assert reg["regressed"] and reg["cur_round"] == 7


def test_perf_gate_insufficient_data_reports_not_fails(tmp_path):
    perf_gate = _load_tool("perf_gate")
    # round 6 has trials, round 7 is single-shot: honest marker, exit 0
    _write_round(tmp_path, 6, [100, 101, 99])
    RunRecord(kind="bench", tool="dmlp_tpu.bench",
              config={"config_id": 1}, metrics={"engine_ms": 400},
              device="cpu", round=7).append_jsonl(
        str(tmp_path / "BENCH_r07.jsonl"))
    rc = perf_gate.main(["--root", str(tmp_path)])
    assert rc == 0
    res = perf_gate.run_gate(str(tmp_path))
    assert not res["regressions"]
    (unq,) = res["unqualified"]
    assert unq["marker"] == "insufficient_trials"


def test_perf_gate_device_mismatch_reports_not_fails(tmp_path):
    perf_gate = _load_tool("perf_gate")
    _write_round(tmp_path, 6, [100, 101, 99])
    RunRecord(kind="bench", tool="dmlp_tpu.bench",
              config={"config_id": 1},
              metrics={"engine_ms": 900,
                       "engine_ms_reps": [899, 900, 901]},
              device="TPU v5 lite", round=7).append_jsonl(
        str(tmp_path / "BENCH_r07.jsonl"))
    rc = perf_gate.main(["--root", str(tmp_path)])
    assert rc == 0
    res = perf_gate.run_gate(str(tmp_path))
    (unq,) = res["unqualified"]
    assert unq["marker"] == "device_mismatch"


def test_perf_gate_within_noise_passes(tmp_path):
    perf_gate = _load_tool("perf_gate")
    _write_round(tmp_path, 6, [100, 104, 96])
    _write_round(tmp_path, 7, [101, 105, 97])    # +1% inside the band
    rc = perf_gate.main(["--root", str(tmp_path)])
    assert rc == 0
    res = perf_gate.run_gate(str(tmp_path))
    (ok,) = res["within_noise"]
    assert ok["significant"] is False


def test_series_deltas_skips_single_round_series(tmp_path):
    _write_round(tmp_path, 6, [100, 101, 99])
    ledger = build_ledger(str(tmp_path))
    assert series_deltas(ledger) == []


# ---------------------------------------------------------------------------
# obs-overhead self-measurement (bench harness)
# ---------------------------------------------------------------------------

@pytest.fixture
def tiny_cfg(monkeypatch):
    """Tiny config 1 so subprocess engine runs stay cheap (the
    test_bench_harness pattern)."""
    from dmlp_tpu.bench import configs as cfgs
    cfg = cfgs.BenchConfig(1, 200, 20, 4, 0.0, 10.0, 1, 8, 4, 7, "tiny.in")
    monkeypatch.setitem(cfgs.BENCH_CONFIGS, 1, cfg)
    return cfg


def test_obs_overhead_recorded_in_runrecord(tiny_cfg, tmp_path):
    """The acceptance path: a bench config records obs_overhead_pct
    measured from real interleaved tracing+counters on/off engine
    runs, and the RunRecord round-trips through the ledger."""
    import io

    from dmlp_tpu.bench.harness import run_config

    buf = io.StringIO()
    record = tmp_path / "BENCH_r06.jsonl"
    res = run_config(1, base_dir=str(tmp_path), out=buf, reps=1,
                     obs_overhead=True, record_path=str(record),
                     timeout_s=240)
    assert res["checksums_match"]
    if "obs_overhead_unavailable" in res:
        pytest.fail(f"overhead A/B did not complete: "
                    f"{res['obs_overhead_unavailable']}")
    assert isinstance(res["obs_overhead_pct"], float)
    assert len(res["engine_ms_obs_off"]) == 1
    assert len(res["engine_ms_obs_on"]) == 1
    rec = json.loads(record.read_text().splitlines()[0])
    assert rec["schema"] == SCHEMA_VERSION
    assert "obs_overhead_pct" in rec["metrics"]
    # and the ledger picks it up as a tracked series
    ledger = build_ledger(str(tmp_path), paths=[str(record)])
    assert any("obs_overhead_pct" in s for s in ledger["series"])


# ---------------------------------------------------------------------------
# migration continuity: RunRecord rounds continue the legacy series
# ---------------------------------------------------------------------------

def test_migrated_emitters_continue_legacy_series_names(tmp_path):
    """The r05->r06 emitter migration must not sever trajectories: a
    dmlp_tpu.bench RunRecord continues harness/configN/*, and the moe/
    ladder tools continue their trainbench/* series — with their trial
    lists attached, so the gate can actually qualify them."""
    RunRecord(kind="bench", tool="dmlp_tpu.bench",
              config={"config_id": 2},
              metrics={"engine_ms": 150,
                       "engine_ms_reps": [148, 150, 153]},
              device="cpu", round=6).append_jsonl(
        str(tmp_path / "BENCH_r06.jsonl"))
    RunRecord(kind="train", tool="tools.trainbench_moe",
              metrics={"a2a_median_ms": 10.0,
                       "a2a_times_ms": [9.8, 10.0, 10.4, 10.1],
                       "dense_median_ms": 12.0,
                       "dense_times_ms": [11.9, 12.0, 12.2, 12.1],
                       "a2a_vs_dense_pct": -16.7},
              device="cpu", round=6).write(
        str(tmp_path / "TRAINBENCH_r06_moe.json"))
    RunRecord(kind="train", tool="tools.bench_offload_ladder",
              metrics={"params_step_time_ms": 5.5, "params_mfu": 0.4},
              device="cpu", round=6).write(
        str(tmp_path / "TRAINBENCH_r06_ladder.json"))

    ledger = build_ledger(str(tmp_path))
    series = ledger["series"]
    (pt,) = series["harness/config2/engine_ms"]
    assert pt["trials"] == [148.0, 150.0, 153.0]
    (moe,) = series["trainbench/moe/a2a/median_ms"]
    assert moe["trials"] == [9.8, 10.0, 10.4, 10.1]
    assert "trainbench/ladder/params/step_time_ms" in series
    assert "trainbench/ladder/params/mfu" in series
    # identifier echoes must NOT become series
    assert not any(s.endswith("/config") for s in series)


def test_migrated_series_qualify_against_legacy_rounds(tmp_path):
    """A legacy HARNESS round and a migrated RunRecord round form ONE
    series; with trials on both sides and the same device the gate
    qualifies the comparison (a regressed migration round fails)."""
    perf_gate = _load_tool("perf_gate")
    with open(tmp_path / "HARNESS_r05.json", "w") as f:
        json.dump({"configs": [{"config": 1, "engine_ms": 100,
                                "engine_ms_reps": [99, 100, 101]}]}, f)
    RunRecord(kind="bench", tool="dmlp_tpu.bench",
              config={"config_id": 1},
              metrics={"engine_ms": 300,
                       "engine_ms_reps": [297, 300, 303]},
              round=6).append_jsonl(str(tmp_path / "BENCH_r06.jsonl"))
    res = perf_gate.run_gate(str(tmp_path))
    (reg,) = res["regressions"]
    assert reg["series"] == "harness/config1/engine_ms"
    assert reg["prev_round"] == 5 and reg["cur_round"] == 6


def test_repairs_metric_is_not_higher_better():
    from dmlp_tpu.obs.ledger import _better_direction
    assert _better_direction(
        "capacity:tools.capacity_beyond_hbm/repairs") != "higher"
    assert _better_direction("bench/qd_pairs_per_sec/x") == "higher"


def test_foreign_device_round_does_not_ungate_prior_pair(tmp_path):
    """Landing one foreign-device round must not disable regression
    detection for the still-comparable earlier rounds: the deltas
    carry BOTH the adjacent (mismatched) pair and the newest
    same-device pair, and the gate still catches a regression there."""
    perf_gate = _load_tool("perf_gate")
    _write_round(tmp_path, 5, [100, 101, 99])
    _write_round(tmp_path, 6, [205, 200, 202])   # regressed, same device
    RunRecord(kind="bench", tool="dmlp_tpu.bench",
              config={"config_id": 1},
              metrics={"engine_ms": 50,
                       "engine_ms_reps": [49, 50, 51]},
              device="TPU v5 lite", round=7).append_jsonl(
        str(tmp_path / "BENCH_r07.jsonl"))
    res = perf_gate.run_gate(str(tmp_path))
    assert [u["marker"] for u in res["unqualified"]] == ["device_mismatch"]
    (reg,) = res["regressions"]          # the r5->r6 cpu pair still gates
    assert (reg["prev_round"], reg["cur_round"]) == (5, 6)
    assert perf_gate.main(["--root", str(tmp_path)]) == 1


def test_unknown_prefix_rNN_artifact_is_discovered(tmp_path):
    """README's contract: ANY _rNN-named artifact at the root is picked
    up — an unknown prefix must produce an entry, not silence."""
    RunRecord(kind="train", tool="custom.tool",
              metrics={"step_time_ms": 4.2}, round=7).write(
        str(tmp_path / "MYSERIES_r07.json"))
    ledger = build_ledger(str(tmp_path))
    (entry,) = ledger["entries"]
    assert entry["source"] == "MYSERIES_r07.json"
    assert entry["status"] == "parsed"
    assert "train:custom.tool/step_time_ms" in ledger["series"]


def test_legacy_bf16_and_capacity_continue_migrated_series():
    """The grandfathered r04 artifacts parse under the MIGRATED
    emitters' series names, so their trajectories survive the
    RunRecord migration (with the bf16 per-arm trials attached)."""
    ledger = build_ledger(REPO)
    pts = ledger["series"]["bench:tools.bench_bf16_staging/f32_median_ms"]
    assert any(p.get("trials") for p in pts)
    assert any(p["round"] == 4 for p in pts)
    caps = ledger["series"]["capacity:tools.capacity_beyond_hbm/solve_wall_s"]
    assert any(p["round"] == 4 for p in caps)
