"""Online serving layer (dmlp_tpu.serve): padding parity, compile-once,
ingestion, gate carry-over, admission control, batching, daemon e2e.

The load-bearing contract: every bucketed/padded micro-batch response
must be BYTE-IDENTICAL to the solo unpadded solve over the same corpus
and to the float64 golden oracle — fuzzed across power-of-two bucket
boundaries (nq and k straddling 8/16/32), with gate carry-over on and
off, before and after incremental ingestion.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.io.report import format_results
from dmlp_tpu.obs import telemetry
from dmlp_tpu.serve import client as sc
from dmlp_tpu.serve import protocol
from dmlp_tpu.serve.admission import AdmissionController
from dmlp_tpu.serve.batching import MicroBatcher, Request
from dmlp_tpu.serve.daemon import ServeDaemon
from dmlp_tpu.serve.engine import (CapacityError, RequestShapeError,
                                   ResidentEngine, k_bucket, query_bucket)


def make_corpus(n=600, na=5, labels=4, seed=3) -> KNNInput:
    rng = np.random.default_rng(seed)
    return KNNInput(Params(n, 0, na),
                    rng.integers(0, labels, n).astype(np.int32),
                    rng.uniform(-10, 10, (n, na)),
                    np.zeros(0, np.int32), np.zeros((0, na)))


def solo_and_golden(corpus: KNNInput, q, ks, config=None):
    inp = KNNInput(Params(corpus.params.num_data, len(ks),
                          corpus.params.num_attrs),
                   corpus.labels, corpus.data_attrs,
                   np.asarray(ks, np.int32), np.asarray(q, np.float64))
    solo = format_results(
        SingleChipEngine(config or EngineConfig()).run(inp))
    gold = format_results(knn_golden(inp))
    assert solo == gold
    return solo


# -- buckets ------------------------------------------------------------------

def test_shape_buckets_are_powers_of_two():
    assert [query_bucket(v) for v in (1, 7, 8, 9, 17)] == \
        [8, 8, 8, 16, 32]
    assert query_bucket(3, granule=128) == 128
    assert [k_bucket(v) for v in (1, 2, 3, 8, 9, 17)] == \
        [1, 2, 4, 8, 16, 32]


# -- padding parity (the tentpole's byte-identity contract) -------------------

def test_padding_parity_fuzz_across_bucket_boundaries():
    """nq and k straddling powers of two: every served batch equals the
    solo solve and the golden oracle byte-for-byte."""
    corpus = make_corpus()
    eng = ResidentEngine(corpus, EngineConfig())
    rng = np.random.default_rng(21)
    for nq in (1, 7, 8, 9, 15, 16, 17):
        for kmax in (1, 7, 8, 9, 16, 17):
            q = rng.uniform(-10, 10, (nq, corpus.params.num_attrs))
            ks = rng.integers(1, kmax + 1, nq).astype(np.int32)
            got = format_results(eng.solve_batch(q, ks))
            assert got == solo_and_golden(corpus, q, ks), \
                f"parity broke at nq={nq} kmax={kmax}"


def test_compile_once_per_bucket_and_no_request_recompilation():
    corpus = make_corpus()
    eng = ResidentEngine(corpus, EngineConfig())
    eng.warmup([(8, 8), (16, 8), (8, 16)])
    c0 = eng.compile_count
    rng = np.random.default_rng(5)
    for nq, k in [(3, 5), (8, 8), (12, 8), (5, 16), (8, 13)]:
        eng.solve_batch(rng.uniform(-10, 10, (nq, 5)),
                        np.full(nq, k, np.int32))
    assert eng.compile_count == c0, \
        "a warmed-bucket request recompiled"
    # a genuinely new bucket compiles exactly once
    eng.solve_batch(rng.uniform(-10, 10, (40, 5)),
                    np.full(40, 4, np.int32))
    assert eng.compile_count == c0 + 1
    eng.solve_batch(rng.uniform(-10, 10, (33, 5)),
                    np.full(33, 3, np.int32))  # same (q64, k4) bucket
    assert eng.compile_count == c0 + 1


def test_warmup_records_cold_start_and_dedups_buckets():
    eng = ResidentEngine(make_corpus(), EngineConfig())
    per = eng.warmup([(8, 8), (7, 7), (3, 5)])   # all one (q8, k8) bucket
    assert len(per) == 1 and eng.compile_count == 1
    assert eng.cold_start_compile_ms is not None \
        and eng.cold_start_compile_ms > 0
    assert eng.bucket_stats()["cold_start_compile_ms"] == \
        eng.cold_start_compile_ms


# -- incremental ingestion ----------------------------------------------------

def test_ingest_parity_and_no_solve_recompilation():
    corpus = make_corpus(n=500)
    eng = ResidentEngine(corpus, EngineConfig(), capacity=1024)
    rng = np.random.default_rng(9)
    q = rng.uniform(-10, 10, (6, 5))
    ks = np.full(6, 9, np.int32)
    eng.solve_batch(q, ks)
    c0 = eng.compile_count
    labels_all = corpus.labels
    attrs_all = corpus.data_attrs
    for m in (1, 7, 64):                        # straddle update buckets
        newl = rng.integers(0, 4, m).astype(np.int32)
        newa = rng.uniform(-10, 10, (m, 5))
        eng.ingest(newl, newa)
        labels_all = np.concatenate([labels_all, newl])
        attrs_all = np.vstack([attrs_all, newa])
        grown = KNNInput(Params(len(labels_all), 0, 5), labels_all,
                         attrs_all, np.zeros(0, np.int32),
                         np.zeros((0, 5)))
        got = format_results(eng.solve_batch(q, ks))
        assert got == solo_and_golden(grown, q, ks), \
            f"ingest parity broke at +{m} rows"
    assert eng.compile_count == c0, "ingestion recompiled a solve"
    assert eng.n_real == 500 + 1 + 7 + 64


def test_ingest_capacity_error():
    eng = ResidentEngine(make_corpus(n=500), EngineConfig(),
                         capacity=512)
    with pytest.raises(CapacityError):
        eng.ingest(np.zeros(600, np.int32), np.zeros((600, 5)))
    # a failed ingest changes nothing
    assert eng.n_real == 500


def test_request_shape_cap():
    eng = ResidentEngine(make_corpus(n=100), EngineConfig(),
                         capacity=128)
    with pytest.raises(RequestShapeError):
        eng.solve_batch(np.zeros((2, 5)), np.full(2, 500, np.int32))


def test_k_beyond_corpus_rows_pads_with_sentinels_like_golden():
    """k in (n_real, capacity] is LEGAL: the reference contract pads
    with id = -1 sentinels when fewer than k candidates exist
    (common.cpp:66), and the golden oracle does the same — a served
    response must match it byte-for-byte, not get rejected."""
    corpus = make_corpus(n=100)
    eng = ResidentEngine(corpus, EngineConfig(), capacity=128)
    rng = np.random.default_rng(8)
    q = rng.uniform(-10, 10, (3, 5))
    ks = np.array([120, 100, 101], np.int32)
    got = eng.solve_batch(q, ks)
    assert got[0].neighbor_ids[-1] == -1          # sentinel tail
    assert format_results(got) == solo_and_golden(corpus, q, ks)


# -- extract path + cross-request gate warm-up --------------------------------

def extract_config():
    return EngineConfig(select="extract", use_pallas=True,
                        data_block=12800)


def test_extract_gate_carry_ab_byte_identical_and_golden():
    """Carry on vs off over multiple batches on the resident extract
    path: identical bytes, both equal to the golden oracle."""
    corpus = make_corpus(n=20000, na=4, seed=31)
    outs = {}
    for carry in (True, False):
        eng = ResidentEngine(corpus, extract_config(), gate_carry=carry)
        texts = []
        for i in range(3):
            rng = np.random.default_rng(400 + i)
            q = rng.uniform(-10, 10, (9, 4))
            ks = rng.integers(1, 9, 9).astype(np.int32)
            texts.append(format_results(eng.solve_batch(q, ks)))
            assert eng.last_extract_impl in ("fused", "extract")
        outs[carry] = texts
    assert outs[True] == outs[False]
    rng = np.random.default_rng(402)
    q = rng.uniform(-10, 10, (9, 4))
    ks = rng.integers(1, 9, 9).astype(np.int32)
    inp = KNNInput(Params(20000, 9, 4), corpus.labels,
                   corpus.data_attrs, ks, q)
    assert outs[True][2] == format_results(knn_golden(inp))


def test_gate_carry_hot_block_ordering_gates_cold_blocks(monkeypatch):
    """Non-vacuous warm-up proof on a norm-banded corpus: the winners
    live in the LAST chunk, so natural order folds them last (cold
    blocks never gate — they fold before any tight threshold exists),
    while carry-over folds the hot chunk first and the far bands gate
    out. Results stay byte-identical either way.

    Pruning is pinned OFF here: the two-stage prune (ops.summaries)
    would skip the far bands before the MXU gate ever sees them —
    exactly the layering this test isolates the gate FROM (the pruned
    composition has its own coverage in tests/test_prune.py)."""
    monkeypatch.setenv("DMLP_TPU_PRUNE", "0")
    rng = np.random.default_rng(55)
    n, na = 38400, 4                       # 3 extract chunks of 12800
    base = rng.uniform(-1.0, 1.0, (n, na))
    attrs = base.copy()
    attrs[:12800] += 600.0                 # far band (never wins)
    attrs[12800:25600] += 300.0            # middle band (never wins)
    corpus = KNNInput(Params(n, 0, na),
                      rng.integers(0, 4, n).astype(np.int32), attrs,
                      np.zeros(0, np.int32), np.zeros((0, na)))
    q = rng.uniform(-1.0, 1.0, (8, na))    # near the 3rd band
    ks = np.full(8, 5, np.int32)
    fracs, texts = {}, {}
    for carry in (True, False):
        eng = ResidentEngine(corpus, extract_config(), gate_carry=carry)
        t = [format_results(eng.solve_batch(q + 0.01 * i, ks))
             for i in range(2)]
        texts[carry] = t[0]
        fracs[carry] = eng.last_gated_fraction
    assert texts[True] == texts[False]
    # First batch teaches the histogram; the second folds the hot
    # (winning) chunk first, so both far bands gate out entirely.
    assert fracs[True] is not None and fracs[True] > 0.5
    assert fracs[True] > (fracs[False] or 0.0)


def test_extract_ingest_into_new_chunk_stays_golden():
    corpus = make_corpus(n=12800, na=4, seed=77)
    eng = ResidentEngine(corpus, extract_config(), capacity=25600)
    rng = np.random.default_rng(6)
    q = rng.uniform(-10, 10, (5, 4))
    ks = np.full(5, 6, np.int32)
    eng.solve_batch(q, ks)
    m = 200                                 # spills into chunk 2
    newl = rng.integers(0, 4, m).astype(np.int32)
    newa = rng.uniform(-10, 10, (m, 4))
    eng.ingest(newl, newa)
    grown = KNNInput(Params(12800 + m, 0, 4),
                     np.concatenate([corpus.labels, newl]),
                     np.vstack([corpus.data_attrs, newa]),
                     np.zeros(0, np.int32), np.zeros((0, 4)))
    got = format_results(eng.solve_batch(q, ks))
    assert got == solo_and_golden(grown, q, ks, extract_config())


# -- admission control --------------------------------------------------------

def test_admission_memory_budget_sheds_before_solve():
    eng = ResidentEngine(make_corpus(), EngineConfig())
    adm = AdmissionController(eng, budget_bytes=1)   # everything over
    d = adm.decide(4, 4, queued_queries=0)
    assert d["verdict"] == "reject" and d["reason"] == "memory"
    adm2 = AdmissionController(eng, budget_bytes=1 << 40)
    assert adm2.decide(4, 4, 0)["verdict"] == "accept"
    assert adm2.headroom_bytes() < (1 << 40)   # model priced in


def test_admission_prices_the_coalesced_batch_not_the_lone_request():
    """64 small admits must not OOM as one coalesced micro-batch: the
    memory check prices min(queued + nq, batch cap) at the queue's
    running kmax, so the budget that admits a lone request refuses the
    same request once the queue it would join is deep."""
    eng = ResidentEngine(make_corpus(), EngineConfig())
    lone = AdmissionController(eng, batch_queries_cap=512)
    lone_need = lone.batch_bytes(8, 4)
    coalesced_need = lone.batch_bytes(512, 4)
    assert coalesced_need > lone_need
    budget = lone._resident_model_bytes() + lone_need + 1
    adm = AdmissionController(eng, budget_bytes=budget,
                              batch_queries_cap=512)
    assert adm.decide(8, 4, queued_queries=0)["verdict"] == "accept"
    d = adm.decide(8, 4, queued_queries=504, queued_kmax=4)
    assert d["verdict"] == "reject" and d["reason"] == "memory"


def test_warmup_honors_k_above_corpus_rows():
    """An explicit warm bucket with n_real < k <= capacity must warm
    THAT k-bucket (k > n_real is a served shape), so the first real
    wide-k request finds it compiled."""
    eng = ResidentEngine(make_corpus(n=100), EngineConfig(),
                         capacity=1024)
    eng.warmup([(4, 512)])
    c0 = eng.compile_count
    rng = np.random.default_rng(3)
    eng.solve_batch(rng.uniform(-10, 10, (4, 5)),
                    np.full(4, 400, np.int32))   # same (q8, k512) bucket
    assert eng.compile_count == c0, \
        "warm-up silently warmed a smaller k-bucket"


def test_admission_rejects_shapes_queue_and_draining():
    eng = ResidentEngine(make_corpus(), EngineConfig())
    adm = AdmissionController(eng, max_queue_queries=10,
                              max_request_queries=8, max_k=16)
    assert adm.decide(9, 4, 0)["reason"] == "shape"
    assert adm.decide(2, 17, 0)["reason"] == "k_too_large"
    assert adm.decide(4, 4, 8)["reason"] == "queue_full"
    adm.draining = True
    assert adm.decide(1, 1, 0)["reason"] == "draining"


def test_admission_injected_squeeze_sheds_without_ladder(monkeypatch):
    from dmlp_tpu.resilience import inject as rs_inject
    from dmlp_tpu.resilience import stats as rs_stats
    rs_stats.reset()
    eng = ResidentEngine(make_corpus(), EngineConfig())
    adm = AdmissionController(eng)
    sched = rs_inject.FaultSchedule.from_dict(
        {"schema": 1, "seed": 0, "faults": [
            {"site": "serve.admit", "kind": "oom", "times": 1}]})
    rs_inject.install(sched)
    try:
        d = adm.decide(2, 2, 0)
        assert d["verdict"] == "reject" \
            and d["reason"] == "injected_squeeze"
        assert adm.decide(2, 2, 0)["verdict"] == "accept"  # once only
    finally:
        rs_inject.uninstall()
    assert rs_stats.snapshot().get("degradations") == []
    assert telemetry.registry().counter("serve.rejected").value(
        label="injected_squeeze") >= 1


# -- micro-batching -----------------------------------------------------------

def test_batcher_coalesces_and_slices_per_request():
    corpus = make_corpus()
    eng = ResidentEngine(corpus, EngineConfig())
    adm = AdmissionController(eng)
    b = MicroBatcher(eng, adm, max_batch_queries=64, tick_s=0.02)
    rng = np.random.default_rng(13)
    reqs = []
    for i in range(5):
        nq = int(rng.integers(1, 7))
        reqs.append(Request(
            kind="query", req_id=str(i),
            query_attrs=rng.uniform(-10, 10, (nq, 5)),
            ks=rng.integers(1, 9, nq).astype(np.int32)))
    b.start()
    try:
        for r in reqs:
            assert b.submit(r)["verdict"] == "accept"
        for r in reqs:
            assert r.done.wait(timeout=120)
    finally:
        b.stop(drain=True)
    assert b.batches < len(reqs), "nothing coalesced"
    for r in reqs:
        assert r.error is None
        got = format_results(r.results)
        assert got == solo_and_golden(corpus, r.query_attrs, r.ks), \
            f"sliced-out request {r.req_id} differs from solo solve"


def test_batcher_drain_finishes_queued_work():
    eng = ResidentEngine(make_corpus(), EngineConfig())
    b = MicroBatcher(eng, AdmissionController(eng), tick_s=0.0)
    rng = np.random.default_rng(2)
    reqs = [Request(kind="query", req_id=str(i),
                    query_attrs=rng.uniform(-10, 10, (2, 5)),
                    ks=np.full(2, 3, np.int32)) for i in range(4)]
    for r in reqs:
        b.submit(r)
    b.start()
    b.stop(drain=True)
    assert all(r.done.is_set() and r.error is None for r in reqs)


def test_batcher_serve_solve_injection_site():
    """The ``serve.solve`` straggler site: a delay fault slows the
    consumer WITHOUT changing answers (the slo_smoke capacity lever);
    a transient fault fails the whole batch visibly and the batcher
    survives it."""
    import time as _time

    from dmlp_tpu.resilience import inject
    from dmlp_tpu.resilience.inject import FaultSchedule

    corpus = make_corpus()
    eng = ResidentEngine(corpus, EngineConfig())
    b = MicroBatcher(eng, AdmissionController(eng), tick_s=0.0)
    rng = np.random.default_rng(7)

    def mkreq(i: int) -> Request:
        return Request(kind="query", req_id=f"inj{i}",
                       query_attrs=rng.uniform(-10, 10, (2, 5)),
                       ks=np.full(2, 3, np.int32))

    b.start()
    try:
        inject.install(FaultSchedule.from_dict(
            {"schema": 1, "seed": 1, "faults": [
                {"site": "serve.solve", "kind": "delay", "ms": 120,
                 "times": 10, "prob": 1.0}]}))
        r = mkreq(0)
        t0 = _time.perf_counter()
        assert b.submit(r)["verdict"] == "accept"
        assert r.done.wait(timeout=120)
        assert r.error is None
        assert _time.perf_counter() - t0 >= 0.12, \
            "delay fault did not slow the batch"
        assert format_results(r.results) == solo_and_golden(
            corpus, r.query_attrs, r.ks), \
            "delay fault perturbed the answers"

        inject.install(FaultSchedule.from_dict(
            {"schema": 1, "seed": 1, "faults": [
                {"site": "serve.solve", "kind": "transient",
                 "times": 1, "prob": 1.0}]}))
        errs0 = telemetry.registry().counter(
            "serve.batch_errors").value()
        r2 = mkreq(1)
        assert b.submit(r2)["verdict"] == "accept"
        assert r2.done.wait(timeout=120)
        assert r2.error is not None \
            and "Injected" in r2.error
        assert telemetry.registry().counter(
            "serve.batch_errors").value() == errs0 + 1

        r3 = mkreq(2)        # the schedule is spent: service resumes
        assert b.submit(r3)["verdict"] == "accept"
        assert r3.done.wait(timeout=120)
        assert r3.error is None
        assert format_results(r3.results) == solo_and_golden(
            corpus, r3.query_attrs, r3.ks)
    finally:
        b.stop(drain=True)
        inject.uninstall()


# -- protocol -----------------------------------------------------------------

def test_protocol_parse_and_errors():
    req = protocol.parse_request(
        json.dumps({"op": "query", "id": "a", "k": 3,
                    "queries": [[1, 2], [3, 4]]}), 2)
    assert req.kind == "query" and req.nq == 2 \
        and list(req.ks) == [3, 3]
    ctl = protocol.parse_request('{"op": "stats"}', 2)
    assert isinstance(ctl, dict)
    for bad in ('{"op": "query"}',
                '{"op": "query", "queries": [[1]]}',        # na mismatch
                '{"op": "query", "k": 0, "queries": [[1, 2]]}',
                '{"op": "query", "ks": [1], "queries": [[1, 2], [3, 4]]}',
                '{"op": "ingest", "rows": [[1, 2]]}',
                'not json', '[1]', '{"op": "wat"}'):
        with pytest.raises(protocol.ProtocolError):
            protocol.parse_request(bad, 2)


# -- daemon end to end (in-process, real sockets) -----------------------------

def test_daemon_end_to_end_replay_ingest_stats_drain():
    corpus = make_corpus(n=800, seed=41)
    d = ServeDaemon(corpus, EngineConfig(), port=0,
                    warm_buckets=[(8, 8), (16, 8)])
    d.start()
    try:
        header = {"serve_trace_schema": 1,
                  "corpus": {"num_attrs": 5, "min_attr": -10,
                             "max_attr": 10}}
        reqs = [{"nq": 1 + (i % 4), "k": 1 + (i % 6), "seed": 800 + i}
                for i in range(8)]
        res = sc.replay(d.port, header, reqs, connections=3)
        assert all(r["ok"] for r in res)
        golden = sc.golden_reference(corpus, header, reqs)
        assert sc.contract_text([r["checksums"] for r in res]) == \
            sc.contract_text(golden)
        cli = sc.ServeClient(d.port)
        st = cli.stats()["stats"]
        assert st["requests_completed"] >= 8
        assert st["engine"]["compile_count"] == d.engine.compile_count
        # wire ingestion + grown-corpus parity
        rng = np.random.default_rng(1)
        newa = rng.uniform(-10, 10, (3, 5))
        r = cli.ingest([0, 1, 2], newa)
        assert r["ok"] and r["corpus_rows"] == 803
        grown = KNNInput(
            Params(803, 0, 5),
            np.concatenate([corpus.labels,
                            np.array([0, 1, 2], np.int32)]),
            np.vstack([corpus.data_attrs, newa]),
            np.zeros(0, np.int32), np.zeros((0, 5)))
        res2 = sc.replay(d.port, header, reqs[:3], connections=2)
        assert [r["checksums"] for r in res2] == \
            sc.golden_reference(grown, header, reqs[:3])
        # malformed line leaves the connection usable
        bad = cli.call({"op": "query"})
        assert not bad["ok"] and "queries" in bad["error"]
        assert cli.stats()["ok"]
        # in-band drain: a request already queued when the drain
        # lands must still get its response before shutdown
        # (the drain waits for handler threads to write).
        late = sc.ServeClient(d.port)
        assert cli.drain()["draining"]
        cli.close()
        t = threading.Thread(target=d.run_until_drained, daemon=True)
        t.start()
        t.join(timeout=60)
        assert not t.is_alive(), "drain hung"
        assert d._inflight == 0
        late.close()
    finally:
        if not d._drain_event.is_set():
            d.close()


def test_daemon_rejections_surface_as_protocol_errors():
    corpus = make_corpus(n=300)
    d = ServeDaemon(corpus, EngineConfig(), port=0, max_k=4,
                    warm_buckets=[(1, 1)])
    d.start()
    try:
        cli = sc.ServeClient(d.port)
        r = cli.query(np.zeros((1, 5)), k=99)
        assert not r["ok"] and "k_too_large" in r["error"]
        r = cli.query(np.zeros((1, 5)), k=2)
        assert r["ok"]
        cli.close()
    finally:
        d.close()


def test_daemon_serve_record_round_trips_ledger(tmp_path):
    rec = tmp_path / "SERVE_TEST_r99.jsonl"
    corpus = make_corpus(n=300)
    d = ServeDaemon(corpus, EngineConfig(), port=0,
                    record_path=str(rec), warm_buckets=[(1, 1)])
    d.start()
    try:
        cli = sc.ServeClient(d.port)
        assert cli.query(np.zeros((2, 5)), k=3)["ok"]
        cli.close()
    finally:
        d.drain()
    from dmlp_tpu.obs.ledger import ingest_file
    entry = ingest_file(str(rec))
    assert entry["status"] == "parsed"
    series = {p["series"] for p in entry["points"]}
    assert "serve/cold_start_compile_ms" in series
    assert "serve/requests_per_sec" in series
    assert any(p["round"] == 99 for p in entry["points"])


# -- concurrent serving: parallel query + ingest + drain ----------------------


def test_concurrent_query_ingest_drain_parity():
    """Parallel query, ingest, and drain connections against ONE
    daemon: every served request's checksums must equal the solo
    solve/golden oracle (today's other daemon tests serialize their
    requests). Ingested rows sit FAR outside the query envelope, so
    the original-corpus oracle is exact under any interleaving — the
    batcher's one consumer thread serializes corpus mutation against
    solves, and this test is the proof."""
    corpus = make_corpus(n=800, seed=17)
    header = {"serve_trace_schema": 1,
              "corpus": {"num_attrs": 5, "min_attr": -10,
                         "max_attr": 10}}
    wave1 = [{"nq": 1 + (w * 5 + i) % 6, "k": 1 + (w + i) % 6,
              "seed": 9000 + w * 100 + i}
             for w in range(3) for i in range(6)]
    wave2 = [{"nq": 2, "k": 3, "seed": 9900 + i} for i in range(6)]
    golden1 = sc.golden_reference(corpus, header, wave1)
    golden2 = sc.golden_reference(corpus, header, wave2)
    d = ServeDaemon(corpus, EngineConfig(), port=0, tick_s=0.001,
                    warm_buckets=[(8, 8), (16, 8)])
    d.start()
    errors, results = [], {}
    try:
        # -- wave 1: 3 query workers + 1 ingest worker, fully parallel
        def query_worker(w):
            try:
                cli = sc.ServeClient(d.port)
                try:
                    for i in range(6):
                        idx = w * 6 + i
                        req = wave1[idx]
                        r = cli.query(
                            sc.materialize_queries(req, header),
                            ks=[int(v) for v in
                                sc.request_ks(req)],
                            req_id=str(idx))
                        results[idx] = r
                finally:
                    cli.close()
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(f"worker {w}: {e}")

        def ingest_worker():
            try:
                rng = np.random.default_rng(3)
                cli = sc.ServeClient(d.port)
                try:
                    for _ in range(4):
                        rows = 1e6 + rng.uniform(0, 1, (3, 5))
                        r = cli.ingest([0, 1, 2], rows)
                        if not r.get("ok"):
                            errors.append(f"ingest: {r}")
                finally:
                    cli.close()
            except Exception as e:  # pragma: no cover
                errors.append(f"ingest: {e}")

        threads = [threading.Thread(target=query_worker, args=(w,),
                                    daemon=True) for w in range(3)]
        threads.append(threading.Thread(target=ingest_worker,
                                        daemon=True))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "wave 1 hung"
        assert not errors, errors
        for idx, want in enumerate(golden1):
            r = results[idx]
            assert r.get("ok"), f"request {idx} failed: {r}"
            assert r["checksums"] == want, \
                f"request {idx} diverged from the solo solve"
        assert d.engine.n_real == 800 + 4 * 3

        # -- wave 2: more queries RACING an in-band drain; every
        # response is either correct or an explicit draining rejection,
        # and queued work still completes (the drain contract)
        out2 = {}

        def late_worker(i):
            try:
                cli = sc.ServeClient(d.port)
                try:
                    req = wave2[i]
                    out2[i] = cli.query(
                        sc.materialize_queries(req, header),
                        ks=[int(v) for v in sc.request_ks(req)],
                        req_id=f"late{i}")
                finally:
                    cli.close()
            except (ConnectionError, OSError):
                # A connection the daemon never ACCEPTED can be reset
                # by the drain — a legal shed, distinct from losing an
                # admitted request's response (which the drain must
                # never do, asserted below).
                out2[i] = {"ok": False, "error": "rejected: draining "
                                                 "(connection reset)"}
            except Exception as e:  # pragma: no cover
                errors.append(f"late {i}: {e}")

        drainer = sc.ServeClient(d.port)
        late = [threading.Thread(target=late_worker, args=(i,),
                                 daemon=True) for i in range(6)]
        for t in late:
            t.start()
        assert drainer.drain()["draining"]
        drainer.close()
        runner = threading.Thread(target=d.run_until_drained,
                                  daemon=True)
        runner.start()
        for t in late:
            t.join(timeout=300)
        runner.join(timeout=300)
        assert not runner.is_alive(), "drain hung under load"
        assert not errors, errors
        served = 0
        for i, r in sorted(out2.items()):
            if r.get("ok"):
                served += 1
                assert r["checksums"] == golden2[i], \
                    f"late request {i} diverged during drain"
            else:
                assert "draining" in r.get("error", ""), r
        assert d._inflight == 0
        # the drain waited for every accepted request's response
        assert served + sum(1 for r in out2.values()
                            if not r.get("ok")) == len(wave2)
    finally:
        if not d._drain_event.is_set():
            d.close()


# -- telemetry drain hook (the PR 9 SIGTERM clean-drain satellite) ------------

def test_sigterm_drain_hook_skips_flight_dump(tmp_path):
    sess = telemetry.start(path=str(tmp_path / "t.prom"),
                           handle_signals=False)
    try:
        fired = []
        sess.set_sigterm_drain(lambda: fired.append(1))
        sess._on_sigterm(15, None)
        assert fired == [1]
        assert not sess.flight.dumped, \
            "drain-hook SIGTERM must not dump a flight artifact"
        events = [e["name"] for e in sess.flight.events()]
        assert "sigterm_drain" in events
    finally:
        sess.set_sigterm_drain(None)
        sess.close()


# -- serve metric names pass the R6 static contract ---------------------------

def test_serve_metric_names_pass_r6():
    import os

    from dmlp_tpu.check.analyzer import analyze_paths
    pkg = os.path.join(os.path.dirname(__file__), "..", "dmlp_tpu",
                       "serve")
    findings = [f for f in analyze_paths([os.path.abspath(pkg)])
                if f.rule.startswith("R6")]
    assert findings == [], [str(f) for f in findings]


# -- memwatch serve model -----------------------------------------------------

def test_serve_memwatch_model_terms_hand_computed():
    from dmlp_tpu.obs import memwatch
    m = memwatch.resident_bytes_model(
        "serve", capacity_rows=1024, na=8, staging="float32",
        qpad=16, kcap=24, extract_chunks=2, chunk_rows=512)
    t = m["terms"]
    assert t["resident_corpus"] == 1024 * 8 * 4
    assert t["labels_ids"] == 1024 * 8
    assert t["extract_chunks"] == 2 * 512 * 8 * 4
    assert t["query_blocks"] == 16 * 8 * 4
    assert t["topk_carries"] == 2 * 16 * 24 * 12
    assert m["total_bytes"] == sum(t.values())
    eng = ResidentEngine(make_corpus(), EngineConfig())
    live = memwatch.model_for_engine(
        eng, eng._batch_input(np.zeros((4, 5)), np.full(4, 3, np.int32)))
    assert live["kind"] == "serve" \
        and live["terms"]["resident_corpus"] > 0
