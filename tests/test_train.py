"""Training extension: learning, dp/tp parity, checkpoint/resume, metrics."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dmlp_tpu.train.data import knn_input_batches, teacher_batches
from dmlp_tpu.train.dryrun import dryrun_train
from dmlp_tpu.train.loop import build_sharded_state, train
from dmlp_tpu.train.metrics import throughput_metrics, train_step_flops
from dmlp_tpu.train.model import init_mlp, num_matmul_params
from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh
from dmlp_tpu.train.step import init_state, make_optimizer, make_train_step


def test_loss_decreases_on_teacher_task():
    state, last = train(steps=60, batch=256, dims=(8, 32, 4),
                        mesh_shape=(1, 1), lr=0.1, log_every=60)
    assert last["loss"] < 1.0  # ~ln(4)=1.39 at init; must have learned
    assert last["accuracy"] > 0.5


def test_dp_tp_sharded_matches_single_device():
    dryrun_train(jax.devices())  # 8 virtual CPU devices (conftest)


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_optimizers_step(opt):
    optimizer = make_optimizer(opt, 1e-2)
    params = init_mlp(jax.random.PRNGKey(0), (4, 8, 3))
    state = init_state(params, optimizer)
    step = make_train_step(optimizer)
    x = np.zeros((16, 4), np.float32)
    y = np.zeros(16, np.int32)
    state, m = step(state, x, y)
    assert int(state["step"]) == 1
    assert np.isfinite(float(m["loss"]))


def test_bfloat16_compute_path():
    optimizer = make_optimizer("sgd", 1e-2)
    params = init_mlp(jax.random.PRNGKey(0), (4, 16, 3))
    state = init_state(params, optimizer)
    step = make_train_step(optimizer, compute_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = rng.integers(0, 3, 32).astype(np.int32)
    state, m = step(state, x, y)
    assert np.isfinite(float(m["loss"]))
    # params stay f32 storage
    assert state["params"]["layer0"]["w"].dtype == jnp.float32


def test_checkpoint_resume_roundtrip(tmp_path):
    ckdir = str(tmp_path / "ck")
    state1, _ = train(steps=5, batch=64, dims=(6, 16, 3), mesh_shape=(1, 1),
                      checkpoint_dir=ckdir, ckpt_every=5, log_every=5)
    # Resume and take 0 extra steps: restored state must equal saved state.
    state2, _ = train(steps=0, batch=64, dims=(6, 16, 3), mesh_shape=(1, 1),
                      checkpoint_dir=ckdir, resume=True, log_every=5)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), state1["params"], state2["params"])
    assert int(state2["step"]) == 5


def test_resume_continues_counting(tmp_path):
    ckdir = str(tmp_path / "ck")
    train(steps=4, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
          checkpoint_dir=ckdir, ckpt_every=4, log_every=4)
    state, _ = train(steps=3, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
                     checkpoint_dir=ckdir, resume=True, log_every=3)
    assert int(state["step"]) == 7


def test_flops_and_throughput_math():
    params = init_mlp(jax.random.PRNGKey(0), (10, 20, 5))
    assert num_matmul_params(params) == 10 * 20 + 20 * 5
    assert train_step_flops(params, 2) == 6.0 * 2 * 300
    m = throughput_metrics(params, batch_size=100, step_time_s=0.5,
                           n_chips=4, peak_per_chip=1e12)
    assert m["samples_per_sec"] == 200.0
    assert m["samples_per_sec_per_chip"] == 50.0
    assert m["mfu"] == pytest.approx(6.0 * 100 * 300 / (0.5 * 4 * 1e12))


def test_knn_input_batches_cycles():
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text
    inp = parse_input_text(generate_input_text(50, 2, 4, 0, 1, 1, 3, 4))
    it = knn_input_batches(inp, batch_size=16)
    for _ in range(5):
        x, y = next(it)
        assert x.shape == (16, 4) and y.shape == (16,)
        assert x.dtype == np.float32 and y.dtype == np.int32


def test_teacher_task_is_deterministic():
    a = next(teacher_batches(4, 3, 8, seed=7))
    b = next(teacher_batches(4, 3, 8, seed=7))
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])


def test_offload_matches_device_resident():
    """Host-DRAM param offload (bench_4 analog): same math as the
    device-resident step; on XLA:CPU the eager fallback runs (in-jit
    streaming is probe-gated to runtimes that compile host placements)."""
    from dmlp_tpu.train.step import make_offload_train_step

    dims = (6, 16, 4)
    mesh = make_train_mesh((2, 2), jax.devices()[:4])
    optimizer = make_optimizer("sgd", 1e-1)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = rng.integers(0, 4, 32).astype(np.int32)

    state_a = build_sharded_state(mesh, dims, optimizer)
    step_a = make_train_step(optimizer)
    state_b = build_sharded_state(mesh, dims, optimizer, offload=True)
    assert state_b["params"]["layer0"]["w"].sharding.memory_kind == "pinned_host"
    step_b = make_offload_train_step(optimizer, state=state_b)
    for _ in range(3):
        state_a, ma = step_a(state_a, x, y)
        state_b, mb = step_b(state_b, x, y)
    assert float(ma["loss"]) == pytest.approx(float(mb["loss"]), rel=1e-6)
    # updated params stayed in host memory across steps
    assert state_b["params"]["layer1"]["w"].sharding.memory_kind == "pinned_host"
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6),
        state_a["params"], state_b["params"])


def test_offload_via_train_loop():
    state, last = train(steps=10, batch=64, dims=(8, 16, 3),
                        mesh_shape=(2, 1), lr=0.05, log_every=10,
                        offload=True)
    assert np.isfinite(last["loss"])
    assert state["params"]["layer0"]["w"].sharding.memory_kind == "pinned_host"


def test_prefetch_to_device_preserves_stream():
    from dmlp_tpu.train.data import prefetch_to_device
    mesh = make_train_mesh((2, 1), jax.devices()[:2])
    shardings = batch_shardings(mesh)
    raw = list(next(teacher_batches(4, 3, 8, seed=3)) for _ in range(5))
    fed = prefetch_to_device(iter(raw), shardings, depth=2)
    got = list(fed)
    assert len(got) == 5
    for (x0, y0), (xd, yd) in zip(raw, got):
        np.testing.assert_array_equal(x0, np.asarray(xd))
        np.testing.assert_array_equal(y0, np.asarray(yd))


def test_weak_scaling_sweep_runs():
    from dmlp_tpu.train.sweep import run_sweep
    pts = run_sweep([1, 2, 4], dims=(8, 16, 4), batch_per_chip=32,
                    steps=3, dtype=None)
    assert [p["n_chips"] for p in pts] == [1, 2, 4]
    for p in pts:
        assert p["samples_per_sec_per_chip"] > 0
        assert p["global_batch"] == 32 * p["n_chips"]


def test_train_bench_smoke(monkeypatch):
    monkeypatch.setenv("TRAIN_DIMS", "8,16,4")
    monkeypatch.setenv("TRAIN_BATCH", "32")
    monkeypatch.setenv("TRAIN_STEPS", "3")
    monkeypatch.setenv("TRAIN_DTYPE", "float32")
    from dmlp_tpu.train.bench import train_bench
    out = train_bench()
    assert out["metric"] == "train_samples_per_sec_per_chip"
    assert out["value"] > 0 and np.isfinite(out["mfu"])


def test_offload_params_level_moments_stay_resident():
    """The "params" offload level: params live in host DRAM, optimizer
    moments stay HBM-resident (half the stream bytes of "all"), and the
    math still matches the fully resident step."""
    from dmlp_tpu.train.step import make_offload_train_step

    dims = (6, 16, 4)
    mesh = make_train_mesh((2, 1), jax.devices()[:2])
    optimizer = make_optimizer("sgd", 1e-1)
    rng = np.random.default_rng(5)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    y = rng.integers(0, 4, 16).astype(np.int32)

    state_a = build_sharded_state(mesh, dims, optimizer)
    step_a = make_train_step(optimizer)
    state_b = build_sharded_state(mesh, dims, optimizer, offload="params")
    assert state_b["params"]["layer0"]["w"].sharding.memory_kind == "pinned_host"
    assert jax.tree.leaves(state_b["opt"])[0].sharding.memory_kind == "device"
    step_b = make_offload_train_step(optimizer, state=state_b)
    for _ in range(3):
        state_a, ma = step_a(state_a, x, y)
        state_b, mb = step_b(state_b, x, y)
    assert float(ma["loss"]) == pytest.approx(float(mb["loss"]), rel=1e-6)
    # placement is preserved across steps on both sides of the split
    assert state_b["params"]["layer1"]["w"].sharding.memory_kind == "pinned_host"
    assert jax.tree.leaves(state_b["opt"])[0].sharding.memory_kind == "device"
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6),
        state_a["params"], state_b["params"])


def test_resolve_offload_level():
    from dmlp_tpu.train.loop import resolve_offload_level

    assert resolve_offload_level(False) == "none"
    assert resolve_offload_level(True) == "all"
    assert resolve_offload_level(None) == "none"
    assert resolve_offload_level("params") == "params"
    with pytest.raises(ValueError):
        resolve_offload_level("moments")


def test_resolve_offload_level_env_style():
    from dmlp_tpu.train.loop import resolve_offload_level

    assert resolve_offload_level("1") == "all"
    assert resolve_offload_level("0") == "none"


def test_train_loop_parallelism_families(tmp_path):
    """The production loop CLI path drives every mesh-parallelism family:
    dp_pp, dp_pp3, and dp_ep train with finite decreasing-ish loss and
    checkpoint/resume round-trips on the pipelined state."""
    state, last = train(steps=8, batch=32, dims=(8, 16, 3),
                        mesh_shape=(1, 4), lr=0.05, log_every=8,
                        parallelism="dp_pp", n_micro=2,
                        checkpoint_dir=str(tmp_path / "ck"), ckpt_every=8)
    assert np.isfinite(last["loss"])
    assert state["params"]["pp_w"].sharding.spec[0] == "pp"

    # resume continues the step counter on the pipelined state
    state2, last2 = train(steps=4, batch=32, dims=(8, 16, 3),
                          mesh_shape=(1, 4), lr=0.05, log_every=4,
                          parallelism="dp_pp", n_micro=2,
                          checkpoint_dir=str(tmp_path / "ck"), resume=True)
    assert last2["step"] == 12

    _, last3 = train(steps=6, batch=32, dims=(8, 16, 3),
                     mesh_shape=(1, 2, 2), lr=0.05, log_every=6,
                     parallelism="dp_pp3", n_micro=2)
    assert np.isfinite(last3["loss"])

    _, last4 = train(steps=6, batch=32, dims=(8, 16, 24, 3),
                     mesh_shape=(1, 4), lr=0.05, log_every=6,
                     parallelism="dp_ep", n_experts=4)
    assert np.isfinite(last4["loss"])


def test_train_loop_moe_a2a_dispatch():
    """VERDICT r4 item 1: the capacity + all-to-all MoE dispatch is
    reachable from the production loop (moe_dispatch="a2a"), trains with
    finite loss, and at cf >= EP (zero drops) its first-step loss equals
    the dense dispatch's on the identical state/batch."""
    common = dict(steps=1, batch=32, dims=(8, 16, 24, 3),
                  mesh_shape=(2, 4), lr=0.05, log_every=1, seed=7,
                  parallelism="dp_ep", n_experts=4)
    _, dense = train(moe_dispatch="dense", **common)
    _, a2a = train(moe_dispatch="a2a", capacity_factor=4.0, **common)
    assert np.isfinite(a2a["loss"])
    assert a2a["loss"] == pytest.approx(dense["loss"], rel=2e-5)

    # Tight capacity (cf=1) still trains — drops go to the residual path.
    _, tight = train(steps=4, batch=32, dims=(8, 16, 24, 3),
                     mesh_shape=(1, 4), lr=0.05, log_every=4, seed=7,
                     parallelism="dp_ep", n_experts=4,
                     moe_dispatch="a2a", capacity_factor=1.0)
    assert np.isfinite(tight["loss"])


def test_train_loop_rejects_inapplicable_flags():
    with pytest.raises(ValueError, match="compute-dtype"):
        train(steps=1, batch=8, dims=(4, 8, 2), mesh_shape=(1, 2),
              parallelism="dp_pp", compute_dtype="bfloat16")
    with pytest.raises(ValueError, match="offload"):
        train(steps=1, batch=8, dims=(4, 8, 2), mesh_shape=(1, 2),
              parallelism="dp_pp", offload="all")
    from dmlp_tpu.train.pipeline import make_axes_mesh
    with pytest.raises(ValueError, match=">= 1"):
        make_axes_mesh({"dp": 1, "pp": 0})
    with pytest.raises(ValueError, match="moe-dispatch"):
        train(steps=1, batch=8, dims=(4, 8, 2), mesh_shape=(1, 2),
              parallelism="dp_pp", moe_dispatch="a2a")
    from dmlp_tpu.train.experts import a2a_capacity
    with pytest.raises(ValueError, match="divisible"):
        a2a_capacity(30, 2, 4)


def test_moe_dispatch_flags_raise_on_dp_tp():
    """--moe-dispatch/--capacity-factor must raise on EVERY non-dp_ep
    family including the default dp_tp (whose branch returns early)."""
    with pytest.raises(ValueError, match="moe-dispatch"):
        train(steps=1, batch=8, dims=(4, 8, 2), mesh_shape=(1, 1),
              parallelism="dp_tp", moe_dispatch="a2a")
    with pytest.raises(ValueError, match="capacity-factor"):
        train(steps=1, batch=8, dims=(4, 8, 2), mesh_shape=(1, 1),
              parallelism="dp_tp", capacity_factor=2.0)
    with pytest.raises(ValueError, match="capacity-factor"):
        train(steps=1, batch=32, dims=(8, 16, 24, 3), mesh_shape=(1, 4),
              parallelism="dp_ep", n_experts=4, moe_dispatch="dense",
              capacity_factor=0.25)


# -- NaN/divergence guard -> checkpoint rollback (resilience) ----------------

def _nan_sched(step, times=1):
    from dmlp_tpu.resilience.inject import FaultSchedule
    return FaultSchedule.from_dict(
        {"schema": 1, "seed": 0, "faults": [
            {"site": "train.step", "kind": "nan", "times": times,
             "when": {"step": step}}]})


@pytest.fixture()
def _resilience_clean():
    from dmlp_tpu.resilience import inject, stats
    stats.reset()
    inject.uninstall()
    yield
    inject.uninstall()
    stats.reset()


def test_nan_guard_rollback_is_step_identical(tmp_path, _resilience_clean):
    """An injected non-finite loss at step 5 rolls back to the latest
    checkpoint and replays; the run must end with EXACTLY the params an
    unfaulted run produces (the chaos harness's train invariant)."""
    from dmlp_tpu.resilience import inject, stats
    kw = dict(steps=6, batch=64, dims=(6, 16, 3), mesh_shape=(1, 1),
              ckpt_every=2, log_every=3, nan_guard=True)
    plain, plain_last = train(checkpoint_dir=str(tmp_path / "ck_a"), **kw)

    inject.install(_nan_sched(step=4))
    faulted, faulted_last = train(checkpoint_dir=str(tmp_path / "ck_b"),
                                  **kw)
    assert stats.snapshot()["rollbacks"] == 1
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), plain["params"], faulted["params"])
    assert plain_last["loss"] == faulted_last["loss"]
    assert plain_last["step"] == faulted_last["step"] == 6


def test_nan_guard_without_checkpoint_dir_raises(_resilience_clean):
    from dmlp_tpu.resilience import inject
    inject.install(_nan_sched(step=1))
    with pytest.raises(RuntimeError, match="no.*checkpoint|checkpoint.*"):
        train(steps=3, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
              log_every=3, nan_guard=True)


def test_nan_guard_persistent_divergence_decays_lr(tmp_path,
                                                   _resilience_clean):
    """The same step diverging twice triggers LR backoff (x0.5) instead
    of an identical-replay livelock; three strikes with max_rollbacks=2
    gives up loudly."""
    from dmlp_tpu.resilience import inject, stats
    inject.install(_nan_sched(step=2, times=2))
    state, _ = train(steps=4, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
                     checkpoint_dir=str(tmp_path / "ck"), ckpt_every=1,
                     log_every=4, nan_guard=True)
    assert stats.snapshot()["rollbacks"] == 2
    assert int(state["step"]) == 4            # recovered and finished

    inject.uninstall()
    stats.reset()
    inject.install(_nan_sched(step=2, times=5))
    with pytest.raises(RuntimeError, match="persisted through"):
        train(steps=4, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
              checkpoint_dir=str(tmp_path / "ck2"), ckpt_every=1,
              log_every=4, nan_guard=True, max_rollbacks=2)


def test_nan_guard_recovers_before_first_periodic_checkpoint(
        tmp_path, _resilience_clean):
    """ckpt_every beyond the faulted step: the guard seeds the dir with
    the start state, so even step 1 divergence is recoverable."""
    from dmlp_tpu.resilience import inject, stats
    inject.install(_nan_sched(step=1))
    state, _ = train(steps=4, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
                     checkpoint_dir=str(tmp_path / "ck"), ckpt_every=100,
                     log_every=4, nan_guard=True)
    assert stats.snapshot()["rollbacks"] == 1
    assert int(state["step"]) == 4


def test_nan_guard_refuses_stale_future_checkpoint(tmp_path,
                                                   _resilience_clean):
    """A checkpoint AHEAD of the faulted step (stale dir from an earlier
    run) must fail loudly — rolling back may never jump forward."""
    from dmlp_tpu.resilience import inject
    ckdir = str(tmp_path / "ck")
    train(steps=6, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
          checkpoint_dir=ckdir, ckpt_every=6, log_every=6)  # leaves step 6
    inject.install(_nan_sched(step=2))
    with pytest.raises(RuntimeError, match="AHEAD"):
        train(steps=6, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
              checkpoint_dir=ckdir, ckpt_every=100, log_every=6,
              nan_guard=True)
