"""Training extension: learning, dp/tp parity, checkpoint/resume, metrics."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dmlp_tpu.train.data import knn_input_batches, teacher_batches
from dmlp_tpu.train.dryrun import dryrun_train
from dmlp_tpu.train.loop import build_sharded_state, train
from dmlp_tpu.train.metrics import throughput_metrics, train_step_flops
from dmlp_tpu.train.model import init_mlp, mlp_apply, num_matmul_params
from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh
from dmlp_tpu.train.step import init_state, make_optimizer, make_train_step


def test_loss_decreases_on_teacher_task():
    state, last = train(steps=60, batch=256, dims=(8, 32, 4),
                        mesh_shape=(1, 1), lr=0.1, log_every=60)
    assert last["loss"] < 1.0  # ~ln(4)=1.39 at init; must have learned
    assert last["accuracy"] > 0.5


def test_dp_tp_sharded_matches_single_device():
    dryrun_train(jax.devices())  # 8 virtual CPU devices (conftest)


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_optimizers_step(opt):
    optimizer = make_optimizer(opt, 1e-2)
    params = init_mlp(jax.random.PRNGKey(0), (4, 8, 3))
    state = init_state(params, optimizer)
    step = make_train_step(optimizer)
    x = np.zeros((16, 4), np.float32)
    y = np.zeros(16, np.int32)
    state, m = step(state, x, y)
    assert int(state["step"]) == 1
    assert np.isfinite(float(m["loss"]))


def test_bfloat16_compute_path():
    optimizer = make_optimizer("sgd", 1e-2)
    params = init_mlp(jax.random.PRNGKey(0), (4, 16, 3))
    state = init_state(params, optimizer)
    step = make_train_step(optimizer, compute_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = rng.integers(0, 3, 32).astype(np.int32)
    state, m = step(state, x, y)
    assert np.isfinite(float(m["loss"]))
    # params stay f32 storage
    assert state["params"]["layer0"]["w"].dtype == jnp.float32


def test_checkpoint_resume_roundtrip(tmp_path):
    ckdir = str(tmp_path / "ck")
    state1, _ = train(steps=5, batch=64, dims=(6, 16, 3), mesh_shape=(1, 1),
                      checkpoint_dir=ckdir, ckpt_every=5, log_every=5)
    # Resume and take 0 extra steps: restored state must equal saved state.
    state2, _ = train(steps=0, batch=64, dims=(6, 16, 3), mesh_shape=(1, 1),
                      checkpoint_dir=ckdir, resume=True, log_every=5)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), state1["params"], state2["params"])
    assert int(state2["step"]) == 5


def test_resume_continues_counting(tmp_path):
    ckdir = str(tmp_path / "ck")
    train(steps=4, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
          checkpoint_dir=ckdir, ckpt_every=4, log_every=4)
    state, _ = train(steps=3, batch=32, dims=(4, 8, 2), mesh_shape=(1, 1),
                     checkpoint_dir=ckdir, resume=True, log_every=3)
    assert int(state["step"]) == 7


def test_flops_and_throughput_math():
    params = init_mlp(jax.random.PRNGKey(0), (10, 20, 5))
    assert num_matmul_params(params) == 10 * 20 + 20 * 5
    assert train_step_flops(params, 2) == 6.0 * 2 * 300
    m = throughput_metrics(params, batch_size=100, step_time_s=0.5,
                           n_chips=4, peak_per_chip=1e12)
    assert m["samples_per_sec"] == 200.0
    assert m["samples_per_sec_per_chip"] == 50.0
    assert m["mfu"] == pytest.approx(6.0 * 100 * 300 / (0.5 * 4 * 1e12))


def test_knn_input_batches_cycles():
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text
    inp = parse_input_text(generate_input_text(50, 2, 4, 0, 1, 1, 3, 4))
    it = knn_input_batches(inp, batch_size=16)
    for _ in range(5):
        x, y = next(it)
        assert x.shape == (16, 4) and y.shape == (16,)
        assert x.dtype == np.float32 and y.dtype == np.int32


def test_teacher_task_is_deterministic():
    a = next(teacher_batches(4, 3, 8, seed=7))
    b = next(teacher_batches(4, 3, 8, seed=7))
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
