"""Native C++ parser: bit-parity with the Python parser + error contract."""

import io

import numpy as np
import pytest

from dmlp_tpu.io import native
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import parse_input, parse_input_text

pytestmark = pytest.mark.skipif(not native.native_available(),
                                reason="g++ / native build unavailable")


def assert_same_input(a, b):
    assert a.params == b.params
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.ks, b.ks)
    # bit-identical doubles: strtod and float() round identically
    np.testing.assert_array_equal(a.data_attrs, b.data_attrs)
    np.testing.assert_array_equal(a.query_attrs, b.query_attrs)


@pytest.mark.parametrize("seed", [1, 2])
def test_native_matches_python(seed):
    text = generate_input_text(300, 40, 7, -1000, 1000, 1, 12, 5, seed=seed)
    assert_same_input(native.parse_input_text_native(text),
                      parse_input_text(text))


def test_native_negative_and_exponent_values():
    text = ("2 1 3\n"
            "0 -1.5 2e-3 300000.125\n"
            "4 .5 -0.000001 1e5\n"
            "Q 2 -1 2.5 3\n")
    assert_same_input(native.parse_input_text_native(text),
                      parse_input_text(text))


def test_native_long_mantissa_strtod_fallback():
    # > 15 significant digits exits the Clinger fast path; strtod must give
    # the same correctly-rounded double as Python float().
    text = ("1 1 2\n"
            "3 0.1234567890123456789 123456789012345678.9\n"
            "Q 1 9.87654321987654321e-7 1.7976931348623157e308\n")
    assert_same_input(native.parse_input_text_native(text),
                      parse_input_text(text))


def test_native_error_contract():
    # Query line not starting with 'Q' (common.cpp:114)
    bad = "1 1 2\n0 1.0 2.0\nX 1 1.0 2.0\n"
    with pytest.raises(ValueError, match="Line is wrongly formatted"):
        native.parse_input_text_native(bad)
    with pytest.raises(ValueError, match="Line is wrongly formatted"):
        parse_input_text(bad)
    # Empty data line (common.cpp:101)
    empty = "2 0 2\n0 1.0 2.0\n\n"
    with pytest.raises(ValueError, match="Line is empty"):
        native.parse_input_text_native(empty)
    with pytest.raises(ValueError, match="Line is empty"):
        parse_input_text(empty)


def test_native_rejects_what_python_rejects():
    # Fractional label: Python's int() raises; native must too (review
    # finding: accept/reject behavior must not flip at the 1MB threshold).
    with pytest.raises(ValueError):
        native.parse_input_text_native("1 0 2\n3.5 1.0 2.0\n")
    with pytest.raises(ValueError):
        parse_input_text("1 0 2\n3.5 1.0 2.0\n")
    # Leading whitespace before 'Q' (Python checks line[0]).
    with pytest.raises(ValueError, match="Line is wrongly formatted"):
        native.parse_input_text_native("1 1 1\n0 1.0\n  Q 1 1.0\n")
    with pytest.raises(ValueError, match="Line is wrongly formatted"):
        parse_input_text("1 1 1\n0 1.0\n  Q 1 1.0\n")


def test_native_accepts_bytes():
    text = generate_input_text(20, 3, 2, 0, 1, 1, 4, 2)
    assert_same_input(native.parse_input_text_native(text.encode("ascii")),
                      parse_input_text(text))


def test_corrupt_so_degrades_to_python(monkeypatch, tmp_path):
    bad = tmp_path / "_bad.so"
    bad.write_bytes(b"not a shared object")
    monkeypatch.setattr(native, "_LIB", str(bad))
    monkeypatch.setattr(native, "_SRC", str(bad))  # mtime check passes
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setattr(native, "_lib", None)
    assert not native.native_available()


def test_native_zero_records():
    text = "0 0 4\n"
    inp = native.parse_input_text_native(text)
    assert inp.params.num_data == 0 and inp.params.num_queries == 0
    assert inp.data_attrs.shape == (0, 4)


def test_parse_input_dispatches_to_native_above_threshold(monkeypatch):
    monkeypatch.setattr("dmlp_tpu.io.grammar._NATIVE_THRESHOLD_BYTES", 1)
    calls = {}
    real = native.parse_input_text_native

    def spy(text):
        calls["native"] = True
        return real(text)
    monkeypatch.setattr(native, "parse_input_text_native", spy)
    text = generate_input_text(50, 5, 3, 0, 1, 1, 4, 2)
    inp = parse_input(io.StringIO(text))
    assert calls.get("native")
    assert inp.params.num_data == 50


@pytest.mark.parametrize("bad_attr", ["1.5abc", "0x10", "1_0", "1.5_0",
                                      "2.e", "--3"])
def test_trailing_garbage_and_underscores_rejected_by_both(bad_attr):
    """ADVICE r1: the fast double path accepted trailing garbage on the
    last attribute; both parsers must reject identically (the reference's
    stringstream extraction would)."""
    good = "2 1 2\n1 1.0 2.0\n0 3.0 %s\nQ 1 1.0 2.0\n"
    text = good % bad_attr
    with pytest.raises(ValueError):
        parse_input_text(text)
    if native.native_available():
        with pytest.raises(ValueError):
            native.parse_input_text_native(text.encode())


@pytest.mark.parametrize("tok", ["2.", ".5", "-2.5", "+3", "inf",
                                 "1e3", "3"])
def test_edge_tokens_agree(tok):
    """Accept/reject AND value parity on edge-case numeric tokens."""
    text = f"1 1 1\n0 {tok}\nQ 1 1.0\n"
    try:
        want = parse_input_text(text)
        py_ok = True
    except ValueError:
        py_ok = False
    if not native.native_available():
        pytest.skip("native parser unavailable")
    try:
        got = native.parse_input_text_native(text.encode())
        nat_ok = True
    except ValueError:
        nat_ok = False
    assert py_ok == nat_ok, tok
    if py_ok:
        assert want.data_attrs[0, 0] == got.data_attrs[0, 0]


def test_native_error_carries_byte_offset():
    """The C side stamps '(byte offset N)' (fastparse.cpp set_err);
    io.native lifts it into the structured ParseError field."""
    from dmlp_tpu.io.grammar import ParseError
    bad = "1 1 2\n0 1.0 2.0\nX 1 1.0 2.0\n"
    with pytest.raises(ParseError) as ei:
        native.parse_input_text_native(bad)
    assert ei.value.byte_offset == bad.index("X 1")


def test_located_error_degrades_on_old_so_message():
    """An old .so without offsets must still yield a ParseError."""
    from dmlp_tpu.io.grammar import ParseError
    from dmlp_tpu.io.native import _located_error
    e = _located_error("Line is empty", 2)
    assert isinstance(e, ParseError) and e.byte_offset is None
    e2 = _located_error("Line is empty (byte offset 42)", 2)
    assert e2.byte_offset == 42
    assert _located_error("", 3).args[0] == "parse error 3"
