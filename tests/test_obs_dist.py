"""Distributed observability: per-rank tracing, trace merge, and the
analytic Pallas kernel-cost models.

Covers obs.dist_trace (rank-pid tracer, clock-sync stamping, rank
metadata), tools/merge_traces.py (clock alignment, rebase, per-rank
span cross-checks, missing-rank failure), tools/check_trace.py --dist,
obs.kernel_cost (analytic extract/distance models, validated against
XLA's cost analysis of the equivalent non-Pallas distance dispatch),
the counters fallback path end to end through a real extract-select
engine run, and obs.comms' pipeline ppermute accounting against
hand-computed byte counts.

The real 2-process cluster form runs where the jax build supports
multi-process CPU computations and SKIPS (same root cause as the seed
suite's 2-process contract failures) where it does not; the merge and
validation chain is covered either way via in-process rank tracers.
"""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from dmlp_tpu.obs import counters as obs_counters
from dmlp_tpu.obs import dist_trace
from dmlp_tpu.obs import kernel_cost
from dmlp_tpu.obs import trace as obs_trace
from dmlp_tpu.obs.comms import pipeline_ppermute_traffic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# obs.dist_trace — the per-rank tracer
# ---------------------------------------------------------------------------

def test_dist_tracer_rank_pid_and_metadata(tmp_path):
    tracer = dist_trace.DistTracer(rank=3, num_ranks=4)
    with tracer.span("work"):
        pass
    tracer.mark_clock_sync()
    path = tracer.write_rank_file(str(tmp_path))
    assert path.endswith("trace-rank03.json")

    doc = json.loads(open(path).read())
    assert doc["dist"]["rank"] == 3
    assert doc["dist"]["num_ranks"] == 4
    assert doc["dist"]["clock_sync_ts_us"] is not None
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert all(e["pid"] == 3 for e in spans)  # rank IS the Perfetto pid
    meta = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "M"}
    assert {"process_name", "process_sort_index", "process_labels"} <= meta
    syncs = [e for e in doc["traceEvents"]
             if e.get("ph") == "i" and e["name"] == "dist.clock_sync"]
    assert len(syncs) == 1


def test_dist_tracer_first_clock_sync_wins():
    tracer = dist_trace.DistTracer(rank=0, num_ranks=1)
    tracer.mark_clock_sync()
    first = tracer._clock_sync_ts_us
    tracer.mark_clock_sync()
    assert tracer._clock_sync_ts_us == first


def test_clock_sync_hook_noop_for_plain_tracer():
    plain = obs_trace.install(obs_trace.Tracer())
    try:
        dist_trace.clock_sync()   # must not raise, must not record
        assert not plain.to_dict()["traceEvents"][1:]
    finally:
        obs_trace.uninstall()
    dist_trace.clock_sync()       # uninstalled: no-op


# ---------------------------------------------------------------------------
# tools/merge_traces.py — alignment, rebase, cross-checks
# ---------------------------------------------------------------------------

def _write_rank(tmp_path, rank, num_ranks, spans=("dist.solve",),
                sync_first=False):
    tracer = dist_trace.DistTracer(rank=rank, num_ranks=num_ranks)
    if sync_first:
        tracer.mark_clock_sync()
    for name in spans:
        with tracer.span(name):
            pass
    if not sync_first:
        tracer.mark_clock_sync()
    tracer.write_rank_file(str(tmp_path))
    return tracer


def test_merge_aligns_clock_sync_and_rebases(tmp_path):
    _write_rank(tmp_path, 0, 2, spans=("dist.read_local_inputs",
                                       "dist.solve"))
    _write_rank(tmp_path, 1, 2, spans=("dist.read_local_inputs",
                                       "dist.solve"))
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))

    assert doc["dist"]["num_ranks"] == 2
    assert doc["dist"]["span_counts"] == {"0": 2, "1": 2}
    ts = [e["ts"] for e in doc["traceEvents"] if "ts" in e]
    assert min(ts) >= 0.0                      # rebased after alignment
    # the two ranks' sync instants land on the same merged timestamp
    syncs = {e["pid"]: e["ts"] for e in doc["traceEvents"]
             if e.get("ph") == "i" and e["name"] == "dist.clock_sync"}
    assert set(syncs) == {0, 1}
    assert abs(syncs[0] - syncs[1]) < 1.0      # us; exact up to rounding
    # per-rank monotonicity in merged order (the --dist check's invariant)
    for pid in (0, 1):
        seq = [e["ts"] for e in doc["traceEvents"]
               if e.get("pid") == pid and "ts" in e]
        assert all(b >= a for a, b in zip(seq, seq[1:]))


def test_merge_marks_missing_rank(tmp_path):
    # Rank 1 of 2 never wrote its file (crashed/never started): the
    # merge proceeds over the surviving rank with the explicit
    # rank_trace_missing marker instead of refusing — the missing rank
    # IS the failure being diagnosed, and the surviving trace is the
    # evidence.
    _write_rank(tmp_path, 0, 2)
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    assert doc["dist"]["num_ranks"] == 2
    marker = doc["dist"]["rank_trace_missing"]
    assert marker["ranks"] == [1]
    assert "missing" in marker["reasons"]["1"]
    # and check_trace --dist accepts the marker (markers never fail)
    merged = tmp_path / "merged.json"
    with open(merged, "w") as f:
        json.dump(doc, f)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
         "--dist", str(merged)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60)
    assert proc.returncode == 0, proc.stderr.decode()


def test_merge_marks_truncated_rank_file(tmp_path):
    # A rank file cut off mid-write (killed process) is invalid JSON:
    # same marker path, with the reason naming the truncation.
    _write_rank(tmp_path, 0, 2)
    _write_rank(tmp_path, 1, 2)
    full = (tmp_path / "trace-rank01.json").read_text()
    (tmp_path / "trace-rank01.json").write_text(full[: len(full) // 2])
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    marker = doc["dist"]["rank_trace_missing"]
    assert marker["ranks"] == [1]
    assert "truncated" in marker["reasons"]["1"]


def test_merge_still_fails_with_no_readable_rank(tmp_path):
    (tmp_path / "trace-rank00.json").write_text("{not json")
    merge_traces = _load_tool("merge_traces")
    with pytest.raises(SystemExit):
        merge_traces.merge(str(tmp_path))


def test_merge_fails_on_divergent_solve_counts(tmp_path):
    _write_rank(tmp_path, 0, 2, spans=("dist.solve", "dist.solve"))
    _write_rank(tmp_path, 1, 2, spans=("dist.solve",))
    merge_traces = _load_tool("merge_traces")
    with pytest.raises(SystemExit):
        merge_traces.merge(str(tmp_path))


def test_check_dist_trace_validates_merged(tmp_path):
    for rank in range(3):
        _write_rank(tmp_path, rank, 3)
    merge_traces = _load_tool("merge_traces")
    merged = tmp_path / "merged.json"
    with open(merged, "w") as f:
        json.dump(merge_traces.merge(str(tmp_path)), f)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
         "--dist", str(merged), "--ranks", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60)
    assert proc.returncode == 0, proc.stderr.decode()

    # and the checker rejects a wrong rank expectation
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
         "--dist", str(merged), "--ranks", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60)
    assert proc.returncode == 1


# ---------------------------------------------------------------------------
# analytic-vs-traced comms reconciliation (ROADMAP item): the
# dist.allgather_candidates span carries real payload bytes + shapes;
# merge_traces recomputes the analytic expectation and embeds the
# per-rank table; check_trace --dist fails any mismatching rank
# ---------------------------------------------------------------------------

def _write_rank_with_allgather(tmp_path, rank, num_ranks, nbytes,
                               shape_args=True):
    """A synthetic rank trace in the DistTracer file format, carrying
    one contract solve span and one allgather span with (optionally)
    the r6 shape args."""
    args = {"nbytes": nbytes}
    if shape_args:
        args.update(ranks=num_ranks, r_shards=2, qpad=16, kcap=8,
                    itemsizes=[8, 4, 4])
    doc = {
        "dist": {"rank": rank, "num_ranks": num_ranks,
                 "clock_sync_ts_us": 100.0},
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": rank, "tid": 0,
             "args": {"name": f"rank {rank}"}},
            {"ph": "i", "name": "dist.clock_sync", "ts": 100.0,
             "pid": rank, "tid": 0, "s": "p"},
            {"ph": "X", "name": "dist.solve", "ts": 110.0, "dur": 5.0,
             "pid": rank, "tid": 0},
            {"ph": "X", "name": "dist.allgather_candidates", "ts": 112.0,
             "dur": 1.0, "pid": rank, "tid": 0, "args": args},
        ],
    }
    with open(tmp_path / f"trace-rank{rank:02d}.json", "w") as f:
        json.dump(doc, f)


def test_merge_reconciles_analytic_vs_traced_allgather_bytes(tmp_path):
    # the REAL payload of a (2, 16, 8) f64+i32+i32 triple: 2*16*8*16 B
    payload = 2 * 16 * 8 * (8 + 4 + 4)
    for rank in range(2):
        _write_rank_with_allgather(tmp_path, rank, 2, payload)
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    rec = doc["dist"]["comms_reconcile"]
    assert set(rec) == {"0", "1"}
    for e in rec.values():
        assert e["traced_bytes"] == payload
        assert e["analytic_bytes"] == payload
        assert e["match"] is True

    check_trace = _load_tool("check_trace")
    merged = tmp_path / "merged.json"
    with open(merged, "w") as f:
        json.dump(doc, f)
    check_trace.check_dist_trace(str(merged))  # must not exit

    # the analytic helper itself: received bytes = (P-1) * payload
    from dmlp_tpu.obs.comms import host_allgather_candidates_traffic
    t = host_allgather_candidates_traffic(2, 2, 16, 8)
    assert t.bytes_out_per_device == payload
    assert t.bytes_in_per_device == payload          # (2-1) * payload


def test_check_dist_trace_fails_on_comms_mismatch(tmp_path):
    payload = 2 * 16 * 8 * 16
    _write_rank_with_allgather(tmp_path, 0, 2, payload)
    _write_rank_with_allgather(tmp_path, 1, 2, payload - 64)  # rank 1 lies
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    assert doc["dist"]["comms_reconcile"]["1"]["match"] is False
    assert doc["dist"]["comms_reconcile"]["0"]["match"] is True

    check_trace = _load_tool("check_trace")
    merged = tmp_path / "merged.json"
    with open(merged, "w") as f:
        json.dump(doc, f)
    with pytest.raises(SystemExit):
        check_trace.check_dist_trace(str(merged))


def test_pre_r6_spans_get_explicit_unavailable_marker(tmp_path):
    for rank in range(2):
        _write_rank_with_allgather(tmp_path, rank, 2, 1024,
                                   shape_args=False)
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    rec = doc["dist"]["comms_reconcile"]
    for e in rec.values():
        assert "analytic_unavailable" in e
        assert "match" not in e          # no false verdict either way
    check_trace = _load_tool("check_trace")
    merged = tmp_path / "merged.json"
    with open(merged, "w") as f:
        json.dump(doc, f)
    check_trace.check_dist_trace(str(merged))  # marker, not a failure


def test_merge_without_allgather_spans_embeds_no_reconcile(tmp_path):
    for rank in range(2):
        _write_rank(tmp_path, rank, 2)
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    assert "comms_reconcile" not in doc["dist"]


# ---------------------------------------------------------------------------
# the real cluster form (spawns OS processes) — skips where the jax build
# cannot run multi-process CPU computations (the seed suite's known drift)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_process_cluster_writes_per_rank_traces(tmp_path):
    from dmlp_tpu.io.datagen import generate_input_text

    # the spawn recipe lives in ONE place: tools/obs_dist_smoke.py
    smoke = _load_tool("obs_dist_smoke")

    text = generate_input_text(211, 23, 5, -4, 4, 1, 12, 4, seed=9)
    path = tmp_path / "in.txt"
    path.write_text(text)
    trace_dir = tmp_path / "traces"

    procs, outs = smoke.spawn_traced_cluster(str(path), str(trace_dir),
                                             procs=2)
    errs = "\n".join(o[1].decode() for o in outs)
    if any(p.returncode != 0 for p in procs):
        if smoke.MULTIPROC_UNSUPPORTED in errs:
            pytest.skip("this jax build cannot run multi-process CPU "
                        "computations (same drift as the seed 2-process "
                        "contract failures)")
        pytest.fail(errs[-2000:])

    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(trace_dir))
    assert doc["dist"]["num_ranks"] == 2
    assert all(v > 0 for v in doc["dist"]["span_counts"].values())


# ---------------------------------------------------------------------------
# obs.kernel_cost — analytic models + counters fallback
# ---------------------------------------------------------------------------

def test_analytic_distance_flops_match_xla_within_5pct():
    """The distance-kernel model's FLOPs vs XLA's cost analysis of the
    equivalent non-Pallas ops.distance dispatch at the same shape."""
    from dmlp_tpu.ops.distance import pairwise_sq_l2

    qb, b, a = 256, 1024, 128
    f = jax.jit(pairwise_sq_l2)
    q = jnp.zeros((qb, a), jnp.float32)
    d = jnp.zeros((b, a), jnp.float32)
    xla = obs_counters.lowered_cost(f, q, d)
    if xla is None:
        pytest.skip("backend exposes no cost model")
    ana = kernel_cost.fused_dist_segmin_cost(qb, b, a)
    # the segmin pass (qb*b flops) is extra work the plain dispatch does
    # not do; compare the shared distance term
    shared = ana["flops"] - qb * b
    assert abs(shared - xla["flops"]) / xla["flops"] < 0.05


def test_analytic_extract_model_scales_with_shape():
    c1 = kernel_cost.extract_topk_cost(128, 12800, 64, 40)
    c2 = kernel_cost.extract_topk_cost(128, 2 * 12800, 64, 40)
    assert c2["flops"] > 1.9 * c1["flops"]
    assert c1["flops"] > 2 * 128 * 12800 * 64          # matmul term floor
    assert c1["bytes_accessed"] >= 12800 * 64 * 4      # one data sweep


def test_probe_resolves_extract_topk_analytically():
    """The acceptance contract: a recorded pallas extract dispatch yields
    analytic flops/bytes, NOT counters_unavailable."""
    from dmlp_tpu.ops.pallas_extract import extract_topk

    probe = obs_counters.CostProbe()
    q = jnp.zeros((128, 8), jnp.float32)
    d = jnp.zeros((1280, 8), jnp.float32)
    probe.record(extract_topk, (q, d), statics=dict(kc=16), count=2,
                 site="single.extract_topk")
    got = probe.collect()
    assert not got.get("counters_unavailable")
    assert got["dispatches_analytic_model"] == 2
    want = kernel_cost.extract_topk_cost(128, 1280, 8, 16)
    assert got["flops"] == pytest.approx(2 * want["flops"])
    assert got["bytes_accessed"] == pytest.approx(
        2 * want["bytes_accessed"])
    assert got["per_site"]["single.extract_topk"]["dispatches"] == 2


def test_analytic_cost_unknown_fn_is_none():
    assert kernel_cost.analytic_cost(lambda x: x, (), {}) is None


def test_extract_cost_measured_iters_term():
    """iters_total turns the extraction term from the deterministic
    lower bound into a measured total (ROADMAP item): strictly more
    flops, marked as measured, linear in the iteration count."""
    base = kernel_cost.extract_topk_cost(128, 12800, 64, 40)
    assert base["extraction_term"] == "modeled_lower_bound"
    m1 = kernel_cost.extract_topk_cost(128, 12800, 64, 40, iters_total=100)
    m2 = kernel_cost.extract_topk_cost(128, 12800, 64, 40, iters_total=200)
    assert m1["extraction_term"] == "measured"
    assert m1["extract_iters_total"] == 100
    assert m1["flops"] > base["flops"]
    assert m2["flops"] - base["flops"] == pytest.approx(
        2 * (m1["flops"] - base["flops"]))
    assert m1["bytes_accessed"] == base["bytes_accessed"]


def test_probe_folds_measured_iters_into_site():
    from dmlp_tpu.ops.pallas_extract import extract_topk

    probe = obs_counters.CostProbe()
    q = jnp.zeros((128, 8), jnp.float32)
    d = jnp.zeros((1280, 8), jnp.float32)
    probe.record(extract_topk, (q, d), statics=dict(kc=16), count=3,
                 site="single.extract_topk")
    probe.record_measured_iters("single.extract_topk", 50,
                                (128, 1280, 8, 16))
    got = probe.collect()
    assert got["extraction_term"] == "measured"
    assert got["extract_iters_total"] == 50
    site = got["per_site"]["single.extract_topk"]
    assert site["extraction_term"] == "measured"
    assert site["extract_iters_total"] == 50
    base = kernel_cost.extract_topk_cost(128, 1280, 8, 16)
    loop = kernel_cost.extract_loop_cost(128, 1280, 8, 16, 50)
    assert got["flops"] == pytest.approx(3 * base["flops"] + loop)


def test_extract_engine_run_reports_measured_extraction_term():
    """End to end: a probed extract engine run reads the kernel's iters
    back post-fence and the collected counters say 'measured'."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text

    inp = parse_input_text(
        generate_input_text(800, 8, 5, 0.0, 20.0, 1, 8, 3, seed=13))
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    probe = obs_counters.install()
    try:
        eng.run(inp)
    finally:
        obs_counters.uninstall()
    got = probe.collect()
    assert got.get("extraction_term") == "measured"
    assert got.get("extract_iters_total", 0) > 0
    site = got["per_site"]["single.extract_topk"]
    assert site["extraction_term"] == "measured"


def test_extract_engine_run_records_analytic_counters():
    """End to end: an extract-select engine run on the interpret-mode
    kernel records analytic counters through the installed probe."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text

    inp = parse_input_text(
        generate_input_text(13000, 16, 6, 0.0, 50.0, 1, 8, 4, seed=7))
    eng = SingleChipEngine(
        EngineConfig(select="extract", use_pallas=True, exact=False))
    probe = obs_counters.install()
    try:
        eng.run(inp)
    finally:
        obs_counters.uninstall()
    assert eng._last_select == "extract"
    got = probe.collect()
    assert not got.get("counters_unavailable")
    assert got.get("dispatches_analytic_model", 0) >= 1
    assert "single.extract_topk" in got.get("per_site", {})
    assert got["per_site"]["single.extract_topk"]["dispatches"] >= 1
    assert got["flops"] > 2 * 13000 * 16 * 6   # at least the matmul term


# ---------------------------------------------------------------------------
# obs.comms — pipeline ppermute accounting (hand-computed, 2x2 mesh)
# ---------------------------------------------------------------------------

def test_pipeline_ppermute_gpipe_2x2_hand_computed():
    # dp=2, pp=2 (the 2x2 mesh), gpipe, M=4 microbatches of (16, 8) f32
    # activations: payload = 16*8*4 = 512 B; ticks = M + S - 1 = 5;
    # links = S - 1 = 1 -> total per group per dispatch = 5 * 512 = 2560.
    # Per device = 2560 / 2 = 1280; bytes_total = 1280 * 2 * 2 groups.
    t = pipeline_ppermute_traffic(2, 4, 16, 8, schedule="gpipe",
                                  n_groups=2)
    assert t.bytes_out_per_device == 1280
    assert t.bytes_total == 5120
    assert t.axis == "pp" and t.axis_size == 2


def test_pipeline_ppermute_interleaved_ring_hand_computed():
    # interleaved: ticks = M - 1 + V*S = 4 - 1 + 2*2 = 7 over the S-link
    # ring -> 7 * 2 * 512 = 7168 per group; per device 3584.
    t = pipeline_ppermute_traffic(2, 4, 16, 8, schedule="interleaved",
                                  n_virtual=2)
    assert t.bytes_out_per_device == 3584
    assert t.bytes_total == 7168


def test_pipeline_ppermute_ticks_match_schedule_ticks():
    """comms restates the schedule arithmetic (it must not import the
    optax-heavy train package); hold the two in sync."""
    from dmlp_tpu.train.pipeline import schedule_ticks

    for sched, v in (("gpipe", 1), ("interleaved", 3)):
        for m, s in ((1, 2), (4, 4), (8, 2)):
            t = pipeline_ppermute_traffic(s, m, 8, 4, schedule=sched,
                                          n_virtual=v)
            ticks = schedule_ticks(sched, m, s, v)
            links = s - 1 if sched == "gpipe" else s
            assert t.bytes_total == ticks * links * 8 * 4 * 4, (sched, m, s)


def test_pipeline_ppermute_single_stage_is_zero():
    # both schedules skip the ppermute entirely at n_stages == 1
    # (train.pipeline dispatches `out` directly) — zero bytes, no phantom
    # single-cell "ring"
    assert pipeline_ppermute_traffic(1, 4, 16, 8).bytes_total == 0
    assert pipeline_ppermute_traffic(
        1, 4, 16, 8, schedule="interleaved", n_virtual=2).bytes_total == 0


def test_train_step_comms_includes_pipeline():
    from dmlp_tpu.obs.comms import summarize, train_step_comms

    traffic = train_step_comms(
        4096, (2, 2), steps=3,
        pipeline={"pp": 2, "n_micro": 4, "micro_rows": 16, "hidden": 8})
    names = {t.collective for t in traffic}
    assert names == {"psum_grads", "ppermute_pipeline"}
    pp = next(t for t in traffic if t.collective == "ppermute_pipeline")
    assert pp.count == 6          # fwd + mirrored bwd, 3 steps
    # per dispatch: 1280 B/device x pp=2 x dp groups=2 = 5120; x count 6
    assert summarize(traffic)["bytes_by_axis"]["pp"] == 6 * 5120


# ---------------------------------------------------------------------------
# emulated per-rank contract runs through the real entry point
# ---------------------------------------------------------------------------

def test_contract_run_with_dist_tracer_records_solve_span(tmp_path):
    """The in-process form of the traced cluster: a DistTracer installed
    around distributed_contract_run captures the dist.* spans and the
    clock-sync stamp, and the per-rank file round-trips the merge."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.parallel.distributed import distributed_contract_run
    from dmlp_tpu.parallel.mesh import make_mesh

    text = generate_input_text(97, 11, 4, 0, 9, 1, 10, 3, seed=4)
    path = tmp_path / "in.txt"
    path.write_text(text)

    for rank in range(2):
        tracer = dist_trace.install(str(tmp_path), rank, 2)
        try:
            engine = ShardedEngine(
                EngineConfig(mode="sharded", query_block=8),
                mesh=make_mesh())
            distributed_contract_run(str(path), engine,
                                     out=open(os.devnull, "w"),
                                     err=open(os.devnull, "w"))
        finally:
            obs_trace.uninstall()
        tracer.write_rank_file(str(tmp_path))

    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    assert doc["dist"]["num_ranks"] == 2
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert "dist.solve" in names
    assert "dist.rescore_local_shards" in names
    assert any(n.startswith("sharded.") for n in names)  # engine spans too


# ---------------------------------------------------------------------------
# straggler/skew analysis + clock-domain metadata (perf-ledger PR)
# ---------------------------------------------------------------------------

def _write_rank_with_solve_dur(tmp_path, rank, num_ranks, solve_dur_us,
                               clock_source=None):
    """Synthetic rank file with a controllable dist.solve duration and
    (optionally) an explicit clock-domain declaration."""
    doc = {
        "dist": {"rank": rank, "num_ranks": num_ranks,
                 "clock_sync_ts_us": 100.0},
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": rank, "tid": 0,
             "args": {"name": f"rank {rank}"}},
            {"ph": "i", "name": "dist.clock_sync", "ts": 100.0,
             "pid": rank, "tid": 0, "s": "p"},
            {"ph": "X", "name": "dist.solve", "ts": 110.0,
             "dur": solve_dur_us, "pid": rank, "tid": 0},
        ],
    }
    if clock_source is not None:
        doc["clock"] = {"source": clock_source}
    with open(tmp_path / f"trace-rank{rank:02d}.json", "w") as f:
        json.dump(doc, f)


def test_tracer_exports_clock_source_metadata():
    doc = obs_trace.Tracer().to_dict()
    assert doc["clock"] == {"source": "monotonic"}
    ddoc = dist_trace.DistTracer(rank=0, num_ranks=1).to_dict()
    assert ddoc["clock"] == {"source": "monotonic"}
    assert ddoc["dist"]["clock_source"] == "monotonic"


def test_merge_embeds_straggler_table_and_flags(tmp_path):
    # rank 1's solve is 3x the median -> flagged at the 1.5x default
    _write_rank_with_solve_dur(tmp_path, 0, 3, 1000.0)
    _write_rank_with_solve_dur(tmp_path, 1, 3, 3000.0)
    _write_rank_with_solve_dur(tmp_path, 2, 3, 1000.0)
    merge_traces = _load_tool("merge_traces")
    doc = merge_traces.merge(str(tmp_path))
    st = doc["dist"]["straggler"]
    assert st["flagged_ranks"] == [1]
    assert st["per_rank"]["1"]["skew_vs_median"] == pytest.approx(3.0)
    assert st["per_rank"]["0"]["skew_vs_median"] == pytest.approx(1.0)
    assert doc["clock"] == {"source": "synced"}

    # balanced ranks -> nothing flagged
    for rank in range(3):
        _write_rank_with_solve_dur(tmp_path, rank, 3, 1000.0)
    st2 = merge_traces.merge(str(tmp_path))["dist"]["straggler"]
    assert st2["flagged_ranks"] == []


def test_straggler_refuses_mixed_clock_domains(tmp_path):
    _write_rank_with_solve_dur(tmp_path, 0, 2, 1000.0,
                               clock_source="synced")
    _write_rank_with_solve_dur(tmp_path, 1, 2, 9000.0)  # monotonic default
    merge_traces = _load_tool("merge_traces")
    st = merge_traces.merge(str(tmp_path))["dist"]["straggler"]
    assert "straggler_unavailable" in st
    assert "mixed clock domains" in st["straggler_unavailable"]
    assert "flagged_ranks" not in st   # no nonsense numbers alongside


def test_check_dist_trace_emits_skew_table_json(tmp_path):
    for rank in range(2):
        _write_rank_with_solve_dur(tmp_path, rank, 2, 1000.0)
    merge_traces = _load_tool("merge_traces")
    merged = tmp_path / "merged.json"
    with open(merged, "w") as f:
        json.dump(merge_traces.merge(str(tmp_path)), f)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
         "--dist", str(merged), "--ranks", "2", "--json"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60)
    assert proc.returncode == 0, proc.stderr.decode()
    verdict = json.loads(proc.stdout.decode())  # stdout is pure JSON
    assert set(verdict["straggler"]["per_rank"]) == {"0", "1"}
    assert verdict["spans_per_rank"] == {"0": 1, "1": 1}


def test_check_dist_trace_fail_on_straggler_opt_in(tmp_path):
    _write_rank_with_solve_dur(tmp_path, 0, 2, 1000.0)
    _write_rank_with_solve_dur(tmp_path, 1, 2, 9000.0)
    merge_traces = _load_tool("merge_traces")
    merged = tmp_path / "merged.json"
    with open(merged, "w") as f:
        json.dump(merge_traces.merge(str(tmp_path)), f)
    argv = [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
            "--dist", str(merged), "--ranks", "2"]
    assert subprocess.run(argv, capture_output=True,
                          timeout=60).returncode == 0   # report-only
    proc = subprocess.run(argv + ["--fail-on-straggler"],
                          capture_output=True, timeout=60)
    assert proc.returncode == 1
    assert b"straggler" in proc.stderr


def test_sharded_engine_reports_measured_extraction_term():
    """The mesh fold outputs now carry per-shard kernel iters: a probed
    ShardedEngine extract run reports extraction_term=measured (the
    ROADMAP follow-on from the autotuner PR)."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text

    inp = parse_input_text(
        generate_input_text(512, 24, 6, 0.0, 20.0, 1, 8, 3, seed=11))
    eng = ShardedEngine(
        EngineConfig(mode="sharded", select="extract", use_pallas=True))
    probe = obs_counters.install()
    try:
        eng.run(inp)
    finally:
        obs_counters.uninstall()
    got = probe.collect()
    assert got.get("extraction_term") == "measured", got
    assert got.get("extract_iters_total", 0) > 0
    site = got["per_site"]["sharded.chunk_fold"]
    assert site["extraction_term"] == "measured"
