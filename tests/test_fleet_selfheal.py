"""Self-healing-fleet tests: corpus signatures + idempotent row-keyed
ingest, the corpus wire op, checksum-driven consistency repair and its
quarantine escalation, router revive hysteresis and the dynamic
replica table, supervisor policy + bounded relaunch + degraded
fallback, the drain/swap race, scrape staleness stamping, and the
mesh gate-carry fold order.

The byte-identity oracle everywhere is the float64 golden model — the
self-healing machinery (signatures, repair, swaps, relaunches) must be
invisible in the response bytes.
"""

import threading
import time

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.fleet import consistency as ccs
from dmlp_tpu.fleet import scrape as fscrape
from dmlp_tpu.fleet.autoscale import (FleetSupervisor, ReplicaSpec,
                                      target_replicas)
from dmlp_tpu.fleet.mesh_engine import MeshResidentEngine
from dmlp_tpu.fleet.reshard import grown_capacity, needs_resplit
from dmlp_tpu.fleet.router import FleetRouter, Replica
from dmlp_tpu.golden.fast import knn_golden_fast
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.serve import client as sc
from dmlp_tpu.serve.daemon import ServeDaemon
from dmlp_tpu.serve.engine import ResidentEngine


def make_corpus(n=600, na=5, labels=4, seed=3, spread=50.0) -> KNNInput:
    rng = np.random.default_rng(seed)
    return KNNInput(
        Params(n, 0, na),
        rng.integers(0, labels, n).astype(np.int32),
        rng.uniform(0, spread, (n, na)),
        np.zeros(0, np.int32), np.zeros((0, na)))


def golden_for(labels, attrs, q, ks):
    inp = KNNInput(Params(len(labels), len(ks), attrs.shape[1]),
                   np.asarray(labels, np.int32), attrs,
                   np.asarray(ks, np.int32), np.asarray(q, np.float64))
    return [r.checksum() for r in knn_golden_fast(inp)]


def _start_daemon(corpus, **kw):
    kw.setdefault("tick_s", 0.001)
    d = ServeDaemon(corpus, kw.pop("config", EngineConfig()), port=0,
                    **kw)
    d.start()
    return d


def _sig(d):
    s = d.engine.corpus_state()
    return (s["rows"], s["checksum"])


# -- corpus signature ----------------------------------------------------------

def test_row_hash_fold_incremental_matches_from_scratch():
    rng = np.random.default_rng(5)
    labels = rng.integers(0, 9, 50).astype(np.int32)
    attrs = rng.uniform(-3, 3, (50, 4))
    full = ccs.corpus_fold(labels, attrs)
    # incremental build in two chunks == from-scratch
    h1 = ccs.row_hashes(labels[:30], attrs[:30])
    h2 = ccs.row_hashes(labels[30:], attrs[30:])
    inc = (ccs.fold_terms(0, h1) + ccs.fold_terms(30, h2)) & ((1 << 64) - 1)
    assert inc == full
    # overwrite with identical content is a no-op
    assert ccs.fold_replace(full, 10, h1[10:20], h1[10:20]) == full
    # overwrite with different content changes it, and replacing back
    # restores it
    other = ccs.row_hashes(labels[:10], attrs[:10] + 1.0)
    changed = ccs.fold_replace(full, 10, h1[10:20], other)
    assert changed != full
    assert ccs.fold_replace(changed, 10, other, h1[10:20]) == full
    # position sensitivity: same rows at different offsets differ
    assert ccs.fold_terms(0, h1) != ccs.fold_terms(1, h1)


def test_diagnose_picks_max_rows_then_majority():
    a = {"rows": 10, "checksum": 111}
    b = {"rows": 12, "checksum": 222}
    assert ccs.diagnose([("r0", a), ("r1", dict(a))]) is None
    v = ccs.diagnose([("r0", a), ("r1", b)])
    assert v["reference"] == "r1" and v["divergent"] == ["r0"]
    # equal rows: the majority signature is the reference
    c = {"rows": 12, "checksum": 333}
    v = ccs.diagnose([("r0", b), ("r1", dict(b)), ("r2", c)])
    assert v["reference"] in ("r0", "r1") and v["divergent"] == ["r2"]


def test_signatures_identical_across_engine_layouts():
    corpus = make_corpus()
    e1 = ResidentEngine(corpus, EngineConfig())
    e2 = MeshResidentEngine(corpus, EngineConfig(mode="sharded"),
                            mesh_shape=(2, 1))
    s1, s2 = e1.corpus_state(), e2.corpus_state()
    assert (s1["rows"], s1["checksum"]) == (s2["rows"], s2["checksum"])
    assert s1["checksum"] == ccs.corpus_fold(corpus.labels,
                                             corpus.data_attrs)
    rng = np.random.default_rng(9)
    newl = rng.integers(0, 4, 7).astype(np.int32)
    newa = rng.uniform(0, 50, (7, 5))
    e1.ingest(newl, newa)
    e2.ingest(newl, newa)
    s1, s2 = e1.corpus_state(), e2.corpus_state()
    assert (s1["rows"], s1["checksum"]) == (s2["rows"], s2["checksum"])


def test_ingest_start_is_idempotent_and_rejects_gaps():
    corpus = make_corpus()
    eng = ResidentEngine(corpus, EngineConfig())
    rng = np.random.default_rng(11)
    newl = rng.integers(0, 4, 5).astype(np.int32)
    newa = rng.uniform(0, 50, (5, 5))
    eng.ingest(newl, newa)
    sig0 = eng.corpus_state()
    # re-delivering the same rows at the same global ids: no-op
    assert eng.ingest(newl, newa, start=600) == 605
    sig1 = eng.corpus_state()
    assert (sig1["rows"], sig1["checksum"]) == (sig0["rows"],
                                                sig0["checksum"])
    assert sig1["epoch"] == sig0["epoch"] + 1
    with pytest.raises(ValueError, match="gap"):
        eng.ingest(newl, newa, start=700)
    # overwrite + solve stays golden against the overwritten corpus
    repl = rng.uniform(0, 50, (5, 5))
    eng.ingest(newl, repl, start=600)
    q = rng.uniform(0, 50, (2, 5))
    ks = np.asarray([4, 6], np.int32)
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    labels = np.concatenate([corpus.labels, newl])
    attrs = np.vstack([corpus.data_attrs, repl])
    assert got == golden_for(labels, attrs, q, ks)


# -- the corpus wire op --------------------------------------------------------

def test_corpus_wire_op_round_trip_and_signature():
    corpus = make_corpus()
    d = _start_daemon(corpus, warm_buckets=[(2, 8)])
    try:
        cli = sc.ServeClient(d.port)
        doc = cli.call({"op": "corpus", "start": 590, "count": 20})
        assert doc["ok"] and doc["corpus_rows"] == 600
        assert len(doc["rows"]) == 10          # clamped to n_real
        assert doc["checksum"] == d.engine.corpus_state()["checksum"]
        np.testing.assert_array_equal(
            np.asarray(doc["rows"]), corpus.data_attrs[590:600])
        # count=0 is the cheap signature probe
        probe = cli.call({"op": "corpus", "count": 0})
        assert probe["ok"] and probe["rows"] == []
        # float64 bits survive the JSON round trip: re-ingesting the
        # fetched rows at their own ids leaves the signature unchanged
        r2 = cli.call({"op": "ingest", "labels": doc["labels"],
                       "rows": doc["rows"], "start": 590})
        assert r2["ok"] and r2["corpus_rows"] == 600
        assert d.engine.corpus_state()["checksum"] == doc["checksum"]
        # malformed starts are protocol errors, not crashes
        bad = cli.call({"op": "corpus", "start": -1})
        assert not bad["ok"]
        bad = cli.call({"op": "ingest", "labels": doc["labels"],
                        "rows": doc["rows"], "start": True})
        assert not bad["ok"]
        cli.close()
    finally:
        d.close()


# -- consistency repair through the router ------------------------------------

def test_prober_detects_and_repairs_dropped_ingest():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    d2 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    router = FleetRouter([("127.0.0.1", d1.port),
                          ("127.0.0.1", d2.port)], port=0,
                         health_interval_s=0.05, divergence_probes=2)
    router.start()
    try:
        rng = np.random.default_rng(13)
        newl = rng.integers(0, 4, 7).astype(np.int32)
        newa = rng.uniform(0, 50, (7, 5))
        # the dropped ingest: rows land on d1 only (as if d2's ingest
        # faulted mid-fan-out)
        cli = sc.ServeClient(d1.port)
        cli.ingest([int(v) for v in newl], newa)
        cli.close()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = router.stats()
            if st["consistency"]["repairs"] >= 1:
                break
            time.sleep(0.05)
        assert st["consistency"]["divergences"] >= 1
        assert st["consistency"]["repairs"] >= 1
        assert st["consistency"]["repaired_rows"] >= 7
        assert _sig(d1) == _sig(d2)
        # the repaired fleet answers the grown oracle from EITHER side
        labels = np.concatenate([corpus.labels, newl])
        attrs = np.vstack([corpus.data_attrs, newa])
        q = rng.uniform(0, 50, (2, 5))
        ks = [4, 6]
        want = golden_for(labels, attrs, q, ks)
        for i in range(4):
            cli = sc.ServeClient(router.port)
            r = cli.query(q, ks=ks, req_id=str(i))
            cli.close()
            assert r["ok"] and r["checksums"] == want
    finally:
        router.close()
        d1.close()
        d2.close()


def test_unrepairable_content_divergence_quarantines():
    corpus = make_corpus()
    ds = [_start_daemon(corpus, warm_buckets=[(2, 8)])
          for _ in range(3)]
    router = FleetRouter([("127.0.0.1", d.port) for d in ds], port=0,
                         health_interval_s=0.05, divergence_probes=2)
    router.start()
    try:
        rng = np.random.default_rng(17)
        # corrupt ONE replica's tail with different content at equal
        # row count: the delta is unknowable -> unrepairable
        bad = rng.uniform(0, 50, (5, 5))
        lab = [int(v) for v in rng.integers(0, 4, 5)]
        cli = sc.ServeClient(ds[2].port)
        r = cli.call({"op": "ingest", "labels": lab,
                      "rows": bad.tolist(), "start": 595})
        cli.close()
        assert r["ok"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = router.stats()
            if st["consistency"]["unrepairable"] >= 1:
                break
            time.sleep(0.05)
        assert st["consistency"]["unrepairable"] >= 1
        quar = [x for x in st["replicas"] if x["quarantined"]]
        assert [q["replica"] for q in quar] == \
            [f"127.0.0.1:{ds[2].port}"]
        # quarantine is terminal: healthy probes do not revive it
        time.sleep(0.3)
        assert not router.find_replica(
            f"127.0.0.1:{ds[2].port}").available()
        # the majority fleet keeps serving golden
        q = rng.uniform(0, 50, (2, 5))
        want = golden_for(corpus.labels, corpus.data_attrs, q, [4, 6])
        cli = sc.ServeClient(router.port)
        resp = cli.query(q, ks=[4, 6])
        cli.close()
        assert resp["ok"] and resp["checksums"] == want
    finally:
        router.close()
        for d in ds:
            d.close()


# -- revive hysteresis ---------------------------------------------------------

def test_revive_hysteresis_requires_consecutive_healthy_probes():
    rep = Replica("127.0.0.1", 1, revive_probes=3)
    assert rep.available()
    rep.probe_fail("boom")
    assert not rep.available()
    rep.probe_ok()
    rep.probe_ok()
    assert not rep.available()       # 2 < 3 consecutive
    rep.probe_ok()
    assert rep.available()           # third consecutive revives
    # a flap resets the streak
    rep.probe_fail("boom again")
    rep.probe_ok()
    assert not rep.available()
    rep.probe_fail("flap")
    rep.probe_ok()
    rep.probe_ok()
    assert not rep.available()
    rep.probe_ok()
    assert rep.available()


def test_router_drain_freeze_is_sticky_against_probes():
    """The re-shard choreography freezes the old replica with
    mark(draining=True) while its DAEMON keeps admission open; a
    health probe reporting draining=False must not un-freeze it (the
    frozen-corpus invariant of the swap's final catch-up)."""
    rep = Replica("127.0.0.1", 1)
    rep.mark(draining=True)
    rep.probe_ok(draining=False)      # the daemon is not draining
    assert not rep.available()        # ...but the router's freeze holds
    rep.mark(draining=False)          # the back-out un-freezes
    rep.probe_ok(draining=False)
    assert rep.available()
    # a daemon-initiated drain still propagates through probes
    rep.probe_ok(draining=True)
    assert not rep.available()
    rep.probe_ok(draining=False)
    assert rep.available()


def test_router_flap_scenario_with_real_probes():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    # health_interval huge: the test drives probes deterministically
    router = FleetRouter([("127.0.0.1", d1.port),
                          ("127.0.0.1", 1)],   # nothing listens on :1
                         port=0, health_interval_s=600,
                         revive_probes=2, repair=False)
    router.start()
    try:
        dead = router.replicas[1]
        router._probe(dead)
        assert not dead.available()
        # "recovery": repoint the dead entry at the live daemon's port
        dead.port = d1.port
        router._probe(dead)
        assert not dead.available()   # first good probe: hysteresis
        router._probe(dead)
        assert dead.available()       # second consecutive: revived
    finally:
        router.close()
        d1.close()


# -- dynamic replica table + the drain/swap race -------------------------------

def test_swap_race_query_wave_none_lost():
    """The re-shard routing-table swap under a racing query wave:
    replacement in, old replica draining then removed, while 12
    clients fire — every request gets exactly one response, every
    response is correct or an explicit rejection, none lost."""
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    d2 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    router = FleetRouter([("127.0.0.1", d1.port)], port=0,
                         health_interval_s=0.05)
    router.start()
    try:
        rng = np.random.default_rng(23)
        q = rng.uniform(0, 50, (2, 5))
        ks = [4, 6]
        want = golden_for(corpus.labels, corpus.data_attrs, q, ks)
        out = [None] * 12

        def worker(i):
            cli = sc.ServeClient(router.port)
            try:
                out[i] = cli.query(q, ks=ks, req_id=str(i))
            finally:
                cli.close()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(12)]
        for t in threads[:5]:
            t.start()
        # the swap: replacement IN, old frozen, old OUT (the
        # reshard.execute_resplit choreography at router level)
        router.add_replica("127.0.0.1", d2.port)
        router.find_replica(f"127.0.0.1:{d1.port}").mark(draining=True)
        for t in threads[5:9]:
            t.start()
        router.remove_replica(f"127.0.0.1:{d1.port}")
        for t in threads[9:]:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(r is not None for r in out)        # none lost
        ok = [r for r in out if r.get("ok")]
        rejected = [r for r in out if not r.get("ok")]
        assert all(r["checksums"] == want for r in ok)
        assert all("rejected" in str(r.get("error", ""))
                   for r in rejected)
        assert len(ok) >= 10   # retry keeps nearly everything served
        names = [r["replica"] for r in router.stats()["replicas"]]
        assert names == [f"127.0.0.1:{d2.port}"]
    finally:
        router.close()
        d1.close()
        d2.close()


# -- supervisor: policy, crash relaunch, budget exhaustion ---------------------

def test_target_replicas_policy():
    assert target_replicas([], 2, 1, 4, 4.0, 0.25) == 2
    assert target_replicas([5, 6, 7], 2, 1, 4, 4.0, 0.25) == 3
    assert target_replicas([5, 6, 7], 4, 1, 4, 4.0, 0.25) == 4  # capped
    assert target_replicas([0, 0, 0.1], 3, 1, 4, 4.0, 0.25) == 2
    assert target_replicas([0, 0, 0], 1, 1, 4, 4.0, 0.25) == 1  # floor
    assert target_replicas([1, 1, 2], 2, 1, 4, 4.0, 0.25) == 2  # steady


class _FakePopen:
    """Controllable stand-in for a replica daemon process."""

    def __init__(self, pid=0):
        self.pid = pid
        self.rc = None
        self.killed = False

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        if self.rc is None:
            self.rc = 0
        return self.rc

    def kill(self):
        self.killed = True
        if self.rc is None:
            self.rc = -9


class _FakeProc:
    def __init__(self, name, port, pid):
        self.name = name
        self.proc = _FakePopen(pid)
        self.ready = {"port": port}
        self.scrape_port = None
        self.errlog = ""


def _supervised_fixture(daemons, budget):
    """Router + supervisor whose 'spawn' hands out in-process daemons
    (deterministic crash/relaunch tests without subprocess latency)."""
    router = FleetRouter([], allow_empty=True, health_interval_s=600,
                         repair=False)
    sup = FleetSupervisor(router, spec=None, min_replicas=1,
                          max_replicas=4, relaunch_budget=budget,
                          unhealthy_deadline_s=0)
    pool = list(daemons)

    def fake_spawn(name, capacity=None):
        if not pool:
            raise RuntimeError("fixture pool exhausted")
        d = pool.pop(0)
        return _FakeProc(name, d.port, pid=9000 + len(pool))

    sup.spawn_proc = fake_spawn
    return router, sup


def test_supervisor_relaunch_and_budget_exhaustion_degrade():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(1, 4)])
    d2 = _start_daemon(corpus, warm_buckets=[(1, 4)])
    router, sup = _supervised_fixture([d1, d2], budget=1)
    try:
        mr = sup.register(sup.spawn_proc("replica_s01"))
        assert [r.name for r in router.replica_list()] == \
            [f"127.0.0.1:{d1.port}"]
        # crash: the fake process exits nonzero
        mr.proc.proc.rc = 1
        sup.poll_once()
        # relaunched onto d2, budget spent
        assert sup.relaunch_budget == 0
        assert [r.name for r in router.replica_list()] == \
            [f"127.0.0.1:{d2.port}"]
        assert [e["reason"] for e in sup.retired] == \
            ["crash: exited rc 1"]
        assert not sup.degraded
        # second crash: budget exhausted -> degraded SMALLER fleet,
        # never a crash loop
        sup.managed[0].proc.proc.rc = -9
        sup.poll_once()
        assert sup.degraded
        assert sup.managed == []
        assert len(router.replica_list()) == 0
        snap = sup.snapshot()
        assert snap["degraded"] and snap["relaunch_budget_left"] == 0
    finally:
        router.close()
        d1.close()
        d2.close()


def test_supervisor_scale_down_uses_drain_choreography():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(1, 4)])
    d2 = _start_daemon(corpus, warm_buckets=[(1, 4)])
    router, sup = _supervised_fixture([d1, d2], budget=0)
    try:
        sup.register(sup.spawn_proc("replica_s01"))
        mr2 = sup.register(sup.spawn_proc("replica_s02"))
        assert len(router.replica_list()) == 2
        rc = sup.retire(mr2, drain=True, reason="scale_down")
        assert rc == 0
        assert len(router.replica_list()) == 1
        assert sup.retired[-1]["reason"] == "scale_down"
        # the drained daemon actually received the in-band drain op
        assert d2._drain_event.is_set()
        assert not d1._drain_event.is_set()
    finally:
        router.close()
        d1.close()
        d2.close()


def test_reshard_planning_helpers():
    assert not needs_resplit(100, 256, threshold=0.9)
    assert needs_resplit(231, 256, threshold=0.9)
    assert grown_capacity(256, 235) == 512
    assert grown_capacity(256, 600) >= 1024


def test_replica_spec_mesh_flags_set_xla_device_count():
    spec = ReplicaSpec("corpus.in", ".", flags=["--mesh", "2x1"])
    env = spec._env()
    assert "xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]
    assert "XLA_FLAGS" not in ReplicaSpec("c.in", ".")._env()


# -- scrape staleness ----------------------------------------------------------

def test_scrape_cache_stamps_age_and_stale():
    calls = {"fail": False}

    def fetch(url):
        if calls["fail"]:
            raise OSError("down")
        return "# TYPE x counter\nx_total 4\n# EOF\n"

    clock = [100.0]
    cache = fscrape.ScrapeCache(clock=lambda: clock[0], fetch=fetch)
    text, age, stale = cache.fetch("a", "http://x/metrics")
    assert text and age == 0.0 and not stale
    calls["fail"] = True
    clock[0] = 103.5
    text2, age2, stale2 = cache.fetch("a", "http://x/metrics")
    assert text2 == text and age2 == pytest.approx(3.5) and stale2
    # a replica never scraped has nothing to reuse
    none_text, _age, none_stale = cache.fetch("b", "http://y/metrics")
    assert none_text is None and none_stale
    cache.forget("a")
    t3, _a3, s3 = cache.fetch("a", "http://x/metrics")
    assert t3 is None and s3


def test_router_metrics_text_marks_stale_replica_scrapes():
    import http.server

    exposition = ("# TYPE serve_requests_completed counter\n"
                  "serve_requests_completed_total 4\n# EOF\n")

    class _H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = exposition.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _H)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    scrape_port = httpd.server_address[1]
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(1, 4)])
    router = FleetRouter([("127.0.0.1", d1.port)],
                         scrape_ports=[scrape_port], port=0,
                         health_interval_s=600, repair=False)
    router.start()
    try:
        from dmlp_tpu.obs.telemetry import validate_openmetrics
        om = router.fleet_metrics_text()
        assert validate_openmetrics(om) == []
        assert "fleet_replica_scrape_age_s" in om
        assert "fleet_replica_scrape_stale" in om
        line = next(ln for ln in om.splitlines()
                    if ln.startswith("fleet_replica_scrape_stale"))
        assert line.endswith(" 0")
        assert "serve_requests_completed_total 4" in om
        # the scrape source dies: counters survive via the cache, but
        # the reuse is STAMPED stale with a nonzero age
        httpd.shutdown()
        httpd.server_close()
        time.sleep(0.05)
        om2 = router.fleet_metrics_text()
        assert validate_openmetrics(om2) == []
        assert "serve_requests_completed_total 4" in om2   # reused
        line = next(ln for ln in om2.splitlines()
                    if ln.startswith("fleet_replica_scrape_stale"))
        assert line.endswith(" 1")
        age_line = next(ln for ln in om2.splitlines()
                        if ln.startswith("fleet_replica_scrape_age_s"))
        assert float(age_line.split()[-1]) >= 0.0
    finally:
        router.close()
        d1.close()


# -- mesh gate-carry (ROADMAP follow-on (e)) -----------------------------------

def _banded_mesh_corpus(n=26000, na=4, seed=29):
    """Norm-banded rows over MULTIPLE per-shard extract chunks (the
    extract chunk granule is pallas_extract.BLOCK_ROWS = 12800 rows,
    so real chunk structure needs > 2 * 12800 rows on a 2-shard mesh).
    The LAST band is far from the others, so queries near it make the
    late (shard, chunk) blocks the hot ones."""
    rng = np.random.default_rng(seed)
    base = rng.uniform(0.0, 1.0, (n, na))
    scale = np.repeat([1.0, 40.0, 400.0], n // 3 + 1)[:n]
    attrs = base + scale[:, None]
    return KNNInput(Params(n, 0, na),
                    rng.integers(0, 4, n).astype(np.int32), attrs,
                    np.zeros(0, np.int32), np.zeros((0, na))), attrs


def test_mesh_gate_carry_reorders_folds_and_stays_byte_identical():
    corpus, attrs = _banded_mesh_corpus()
    cfg = EngineConfig(mode="sharded", select="extract",
                       use_pallas=True, data_block=12800)
    ks = np.asarray([6, 6], np.int32)
    on = MeshResidentEngine(corpus, cfg, mesh_shape=(2, 1),
                            gate_carry=True)
    off = MeshResidentEngine(corpus, cfg, mesh_shape=(2, 1),
                             gate_carry=False)
    assert on._nchunks > 1           # reordering needs real chunks
    on.warmup([(2, 6)])
    off.warmup([(2, 6)])
    for seed in (1, 2, 3, 4):
        qq = attrs[-3:-1] + 0.01 * seed    # near the LAST band
        want = golden_for(corpus.labels, attrs, qq, ks)
        got_on = [r.checksum() for r in on.solve_batch(qq, ks)]
        got_off = [r.checksum() for r in off.solve_batch(qq, ks)]
        assert got_on == got_off == want
    # Non-vacuity: the per-(shard, chunk) histogram attributed the
    # winners, and a LATE chunk now folds FIRST (off stays natural).
    assert on._block_hits.shape == (2, on._nchunks)
    assert on._block_hits.sum() > 0
    hot = int(np.argmax(on._block_hits.sum(axis=0)))
    assert hot != 0                  # the hot band lives in a late chunk
    assert on._chunk_order()[0] == hot
    assert off._chunk_order() == list(range(off._nchunks))
    # ...and the reordered fold is still golden (assert again after
    # the order actually changed)
    q = attrs[-3:-1] + 0.01
    got = [r.checksum() for r in on.solve_batch(q, ks)]
    assert got == golden_for(corpus.labels, attrs, q, ks)
    assert on.last_gated_fraction is not None
    assert on.bucket_stats()["gate_carry"] is True
    # Per-shard attribution: band-0 queries credit shard 0's row only
    # (shard 1 holds nothing but the last band's tail).
    before = on._block_hits.copy()
    q0 = attrs[:2] + 0.01
    got0 = [r.checksum() for r in on.solve_batch(
        q0, np.asarray([4, 4], np.int32))]
    assert got0 == golden_for(corpus.labels, attrs, q0, [4, 4])
    delta = on._block_hits - before
    assert delta[0].sum() > 0
    assert delta[1].sum() == 0


# -- daemon stats carry the corpus block ---------------------------------------

def test_daemon_stats_expose_corpus_signature():
    corpus = make_corpus()
    d = _start_daemon(corpus, warm_buckets=[(1, 4)])
    try:
        cli = sc.ServeClient(d.port)
        st = cli.stats()["stats"]
        cli.close()
        assert st["corpus"]["rows"] == 600
        assert st["corpus"]["checksum"] == \
            ccs.corpus_fold(corpus.labels, corpus.data_attrs)
        assert st["corpus"]["epoch"] == 0
    finally:
        d.close()
