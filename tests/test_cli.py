"""End-to-end CLI tests: stdin grammar -> stdout checksums + stderr timing."""

import io
import re

import pytest

from dmlp_tpu.cli import main
from dmlp_tpu.golden.reference import solve_text
from dmlp_tpu.io.datagen import generate_input_text


def run_cli(args, text):
    out, err = io.StringIO(), io.StringIO()
    rc = main(args, stdin=io.StringIO(text), stdout=out, stderr=err)
    assert rc == 0
    return out.getvalue(), err.getvalue()


def test_cli_checksums_match_golden():
    text = generate_input_text(120, 15, 6, -2, 2, 1, 10, 4, seed=33)
    out, err = run_cli(["--data-block", "32", "--query-block", "8"], text)
    assert out == solve_text(text)
    # the stderr metrics contract line (common.cpp:130)
    assert re.search(r"^Time taken: \d+ ms$", err, re.M)


@pytest.mark.parametrize("mode", ["single", "sharded", "ring"])
def test_cli_every_mode_matches_golden(mode):
    # Guards the CLI registry: every --mode must resolve and give
    # golden-identical output.
    text = generate_input_text(90, 11, 4, -3, 3, 1, 7, 3, seed=44)
    out, _ = run_cli(["--mode", mode], text)
    assert out == solve_text(text)


def test_cli_debug_mode_matches_golden_debug():
    text = generate_input_text(40, 5, 3, 0, 1, 2, 5, 3, seed=8)
    out, _ = run_cli(["--debug"], text)
    assert out == solve_text(text, debug=True)
    assert out.startswith("Label for Query 0 : ")
    assert "Top-" in out and " : " in out


def test_cli_golden_engine_mode():
    text = generate_input_text(30, 4, 2, 0, 1, 1, 4, 2, seed=2)
    out, _ = run_cli(["--engine", "golden"], text)
    assert out == solve_text(text)


def test_cli_device_full_and_phase_times():
    text = generate_input_text(64, 8, 4, 0, 8, 1, 6, 3, seed=13)
    out, err = run_cli(["--device-full", "--fast", "--phase-times"], text)
    # f32 device pipeline on generator data: checksums still match golden
    # in practice for this size/seed (validated here; exact-mode tests are
    # the guarantee).
    assert out == solve_text(text)
    assert "phase parse:" in err
