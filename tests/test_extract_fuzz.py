"""Randomized differential sweep of the extraction path (CPU interpret).

The fixed tests cover designed cases; this sweep hardens the flagship
select="extract" engine against shape edge cases: random sizes straddling
pad granules and duplicate-heavy grids (seed sweep), plus dedicated
k == n / single-query / 1-point cases the random seeds don't reach —
every case diffs against the float64 golden model, so any algorithmic or
padding bug is a checksum mismatch, not a tolerance judgement.
"""

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params
from tests.test_engine_single import assert_same_results


def _case(seed: int) -> KNNInput:
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 700))
    nq = int(rng.integers(1, 40))
    na = int(rng.integers(1, 9))
    dup = rng.random() < 0.4
    if dup:  # integer grid: exact f32 + massive tie groups
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    else:
        data = rng.uniform(-20, 20, (n, na))
        queries = rng.uniform(-20, 20, (nq, na))
    labels = rng.integers(0, int(rng.integers(1, 6)) + 1, n).astype(np.int32)
    kmax = int(rng.integers(1, min(n, 48) + 1))
    ks = rng.integers(1, kmax + 1, nq).astype(np.int32)
    if rng.random() < 0.25:
        ks[0] = min(n, 48)  # k at (or near) the dataset size
    return KNNInput(Params(n, nq, na), labels, data, ks, queries)


@pytest.mark.parametrize("seed", range(101, 119))
def test_extract_engine_random_shapes_match_golden(seed):
    inp = _case(seed)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


@pytest.mark.parametrize("n,nq,kfull", [(37, 5, True), (48, 1, True),
                                        (513, 1, False), (1, 3, True)])
def test_extract_engine_kn_and_single_query_edges(n, nq, kfull):
    """The edge cases random seeds don't reach: k == n (every real point
    is a neighbor; sentinel padding must fill the rest), a single query
    row, and a 1-point dataset."""
    rng = np.random.default_rng(7 * n + nq)
    na = 4
    data = rng.uniform(-5, 5, (n, na))
    queries = rng.uniform(-5, 5, (nq, na))
    labels = rng.integers(0, 3, n).astype(np.int32)
    ks = np.full(nq, n if kfull else 48, np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


@pytest.mark.parametrize("seed", [301, 302, 303])
def test_extract_engine_fast_mode_random_dup_grids(seed):
    # fast mode (no f64 rescore) on exact-in-f32 integer grids: the
    # boundary-overflow repair alone must deliver golden parity.
    rng = np.random.default_rng(seed)
    n, nq, na = int(rng.integers(300, 900)), int(rng.integers(4, 24)), 3
    data = rng.integers(0, 4, (n, na)).astype(np.float64)
    queries = rng.integers(0, 4, (nq, na)).astype(np.float64)
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = rng.integers(1, 32, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True,
                                        exact=False))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


def test_extract_engine_k_beyond_kernel_cap_routes_outliers():
    """VERDICT r3 item 4 follow-through: k in the thousands is legal input
    (generate_input.py:19 allows k up to num_data), but the extraction
    kernel caps kc at 512 (pallas_extract.supports). The heterogeneous-k
    router keeps the kernel for queries whose kcap fits and streams only
    the wide-k outliers (sharing the staged chunks) — and the merged
    results still match the float64 golden model exactly."""
    rng = np.random.default_rng(77)
    n, nq, na = 2000, 6, 4
    data = rng.uniform(-30, 30, (n, na))
    queries = rng.uniform(-30, 30, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = np.array([700, 1, 640, 2000, 513, 512], np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"   # bulk stayed on the kernel
    assert eng.last_hetk == (1, 5)         # (bulk, outlier) query counts
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_extract_engine_all_huge_k_multipass():
    """When EVERY query's k exceeds the kernel's width there is no bulk to
    route — r4 dropped to the streaming select; r5 runs the kernel in
    floor-raised multi-passes (VERDICT r4 item 2) and must land on golden
    with heterogeneous wide ks (kcap sized by the max)."""
    rng = np.random.default_rng(80)
    n, nq, na = 1200, 4, 3
    data = rng.uniform(-10, 10, (n, na))
    queries = rng.uniform(-10, 10, (nq, na))
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = np.array([600, 700, 1200, 997], np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert eng.last_hetk is None
    assert eng.last_mp_passes >= 2
    assert_same_results(got, knn_golden(inp), check_dists=False)


@pytest.mark.parametrize("seed", [201, 202, 203])
def test_hetk_routing_random_mixed_k_matches_golden(seed):
    """Randomized mixed-k inputs: most queries small-k, a random few in
    the hundreds-to-n range, duplicate-heavy ~half the time. Exercises
    the split plan, the shared-chunk outlier fold, the per-segment
    tie-overflow repair, and the index merge."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(600, 2200))
    nq = int(rng.integers(3, 30))
    na = int(rng.integers(1, 7))
    if rng.random() < 0.5:
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    else:
        data = rng.uniform(-20, 20, (n, na))
        queries = rng.uniform(-20, 20, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 40, nq).astype(np.int32)
    n_out = int(rng.integers(1, max(2, nq // 3)))
    out_rows = rng.choice(nq, n_out, replace=False)
    ks[out_rows] = rng.integers(520, n + 1, n_out)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng.last_hetk == (nq - n_out, n_out)
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_hetk_routing_device_full_and_fast_mode():
    """The router also serves run_device_full and fast (exact=False) mode;
    integer attrs make the f32 device ordering exact, so both must equal
    golden."""
    rng = np.random.default_rng(88)
    n, nq, na = 1500, 10, 4
    data = rng.integers(-7, 8, (n, na)).astype(np.float64)
    queries = rng.integers(-7, 8, (nq, na)).astype(np.float64)
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = rng.integers(1, 30, nq).astype(np.int32)
    ks[2], ks[7] = 900, 1500
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    want = knn_golden(inp)

    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True,
                                        exact=False))
    got = eng.run(inp)
    assert eng.last_hetk == (8, 2)
    assert_same_results(got, want, check_dists=False)

    # Device-full keeps the device's f32 tie handling (no host repair by
    # contract), so its routing check uses continuous data where ties
    # don't arise; the tie-heavy grid above already covered run()'s
    # repair across segments.
    data_c = rng.uniform(-50, 50, (n, na))
    queries_c = rng.uniform(-50, 50, (nq, na))
    inp_c = KNNInput(Params(n, nq, na), labels, data_c, ks, queries_c)
    want_c = knn_golden(inp_c)
    eng2 = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    full = eng2.run_device_full(inp_c)
    assert eng2.last_hetk == (8, 2)
    for g, w in zip(full, want_c):
        assert g.query_id == w.query_id
        assert g.predicted_label == w.predicted_label
        assert list(g.neighbor_ids) == list(w.neighbor_ids)
        assert g.checksum() == w.checksum()


def test_sharded_extract_k_beyond_kernel_cap_routes_outliers():
    """The mesh engines route heterogeneous k too: the chunked driver
    keeps the extraction kernel for the bulk and folds the wide-k
    outliers on the SAME staged chunks (streaming mesh program), with
    golden parity on the merged results."""
    import jax
    import pytest as _pytest

    from dmlp_tpu.engine.sharded import ShardedEngine

    if len(jax.devices()) < 8:
        _pytest.skip("needs 8 devices")
    rng = np.random.default_rng(78)
    n, nq, na = 1500, 5, 3
    data = rng.uniform(-9, 9, (n, na))
    queries = rng.uniform(-9, 9, (nq, na))
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = np.array([600, 1, 1500, 520, 3], np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = ShardedEngine(EngineConfig(mode="sharded", select="extract",
                                     use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"   # bulk stayed on the kernel
    assert eng.last_hetk == (2, 3)
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_ring_hetk_routing_matches_golden():
    """Ring merge strategy serves both router segments (outlier lists
    merge by ring all-reduce too); device-full stays unrouted-compatible
    via the same segment loop."""
    import jax
    import pytest as _pytest

    from dmlp_tpu.engine.ring import RingEngine

    if len(jax.devices()) < 8:
        _pytest.skip("needs 8 devices")
    rng = np.random.default_rng(81)
    n, nq, na = 1100, 9, 4
    data = rng.uniform(0, 50, (n, na))
    queries = rng.uniform(0, 50, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 20, nq).astype(np.int32)
    ks[4], ks[8] = 700, 1100
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    want = knn_golden(inp)
    eng = RingEngine(EngineConfig(mode="ring", select="extract",
                                  use_pallas=True))
    got = eng.run(inp)
    assert eng.last_hetk == (7, 2)
    assert_same_results(got, want, check_dists=False)

    full = eng.run_device_full(inp)
    assert eng.last_hetk == (7, 2)
    for g, w in zip(full, want):
        assert g.query_id == w.query_id
        assert g.checksum() == w.checksum()


def test_extract_engine_wide_k_tuned_variant():
    """k > 64 routes to the wide-list tuned variant (tq=64, ne=4,
    SWEEP_WIDEK_r04); parity must hold there too."""
    rng = np.random.default_rng(79)
    n, nq, na = 1400, 9, 5
    data = rng.uniform(-15, 15, (n, na))
    queries = rng.uniform(-15, 15, (nq, na))
    labels = rng.integers(0, 6, n).astype(np.int32)
    ks = rng.integers(100, 201, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert_same_results(got, knn_golden(inp), check_dists=False)
