"""Randomized differential sweep of the extraction path (CPU interpret).

The fixed tests cover designed cases; this sweep hardens the flagship
select="extract" engine against shape edge cases: random sizes straddling
pad granules and duplicate-heavy grids (seed sweep), plus dedicated
k == n / single-query / 1-point cases the random seeds don't reach —
every case diffs against the float64 golden model, so any algorithmic or
padding bug is a checksum mismatch, not a tolerance judgement.
"""

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params
from tests.test_engine_single import assert_same_results


def _case(seed: int) -> KNNInput:
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 700))
    nq = int(rng.integers(1, 40))
    na = int(rng.integers(1, 9))
    dup = rng.random() < 0.4
    if dup:  # integer grid: exact f32 + massive tie groups
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    else:
        data = rng.uniform(-20, 20, (n, na))
        queries = rng.uniform(-20, 20, (nq, na))
    labels = rng.integers(0, int(rng.integers(1, 6)) + 1, n).astype(np.int32)
    kmax = int(rng.integers(1, min(n, 48) + 1))
    ks = rng.integers(1, kmax + 1, nq).astype(np.int32)
    if rng.random() < 0.25:
        ks[0] = min(n, 48)  # k at (or near) the dataset size
    return KNNInput(Params(n, nq, na), labels, data, ks, queries)


@pytest.mark.parametrize("seed", range(101, 119))
def test_extract_engine_random_shapes_match_golden(seed):
    inp = _case(seed)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


@pytest.mark.parametrize("n,nq,kfull", [(37, 5, True), (48, 1, True),
                                        (513, 1, False), (1, 3, True)])
def test_extract_engine_kn_and_single_query_edges(n, nq, kfull):
    """The edge cases random seeds don't reach: k == n (every real point
    is a neighbor; sentinel padding must fill the rest), a single query
    row, and a 1-point dataset."""
    rng = np.random.default_rng(7 * n + nq)
    na = 4
    data = rng.uniform(-5, 5, (n, na))
    queries = rng.uniform(-5, 5, (nq, na))
    labels = rng.integers(0, 3, n).astype(np.int32)
    ks = np.full(nq, n if kfull else 48, np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


@pytest.mark.parametrize("seed", [301, 302, 303])
def test_extract_engine_fast_mode_random_dup_grids(seed):
    # fast mode (no f64 rescore) on exact-in-f32 integer grids: the
    # boundary-overflow repair alone must deliver golden parity.
    rng = np.random.default_rng(seed)
    n, nq, na = int(rng.integers(300, 900)), int(rng.integers(4, 24)), 3
    data = rng.integers(0, 4, (n, na)).astype(np.float64)
    queries = rng.integers(0, 4, (nq, na)).astype(np.float64)
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = rng.integers(1, 32, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True,
                                        exact=False))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


def test_extract_engine_k_beyond_kernel_cap_routes_outliers():
    """VERDICT r3 item 4 follow-through: k in the thousands is legal input
    (generate_input.py:19 allows k up to num_data), but the extraction
    kernel caps kc at 512 (pallas_extract.supports). The heterogeneous-k
    router keeps the kernel for queries whose kcap fits and streams only
    the wide-k outliers (sharing the staged chunks) — and the merged
    results still match the float64 golden model exactly."""
    rng = np.random.default_rng(77)
    n, nq, na = 2000, 6, 4
    data = rng.uniform(-30, 30, (n, na))
    queries = rng.uniform(-30, 30, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = np.array([700, 1, 640, 2000, 513, 512], np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"   # bulk stayed on the kernel
    assert eng.last_hetk == (1, 5)         # (bulk, outlier) query counts
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_extract_engine_all_huge_k_multipass():
    """When EVERY query's k exceeds the kernel's width there is no bulk to
    route — r4 dropped to the streaming select; r5 runs the kernel in
    floor-raised multi-passes (VERDICT r4 item 2) and must land on golden
    with heterogeneous wide ks (kcap sized by the max)."""
    rng = np.random.default_rng(80)
    n, nq, na = 1200, 4, 3
    data = rng.uniform(-10, 10, (n, na))
    queries = rng.uniform(-10, 10, (nq, na))
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = np.array([600, 700, 1200, 997], np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert eng.last_hetk is None
    assert eng.last_mp_passes >= 2
    assert_same_results(got, knn_golden(inp), check_dists=False)


@pytest.mark.parametrize("seed", [201, 202, 203])
def test_hetk_routing_random_mixed_k_matches_golden(seed):
    """Randomized mixed-k inputs: most queries small-k, a random few in
    the hundreds-to-n range, duplicate-heavy ~half the time. Exercises
    the split plan, the shared-chunk outlier fold, the per-segment
    tie-overflow repair, and the index merge."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(600, 2200))
    nq = int(rng.integers(3, 30))
    na = int(rng.integers(1, 7))
    if rng.random() < 0.5:
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    else:
        data = rng.uniform(-20, 20, (n, na))
        queries = rng.uniform(-20, 20, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 40, nq).astype(np.int32)
    n_out = int(rng.integers(1, max(2, nq // 3)))
    out_rows = rng.choice(nq, n_out, replace=False)
    ks[out_rows] = rng.integers(520, n + 1, n_out)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng.last_hetk == (nq - n_out, n_out)
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_hetk_routing_device_full_and_fast_mode():
    """The router also serves run_device_full and fast (exact=False) mode;
    integer attrs make the f32 device ordering exact, so both must equal
    golden."""
    rng = np.random.default_rng(88)
    n, nq, na = 1500, 10, 4
    data = rng.integers(-7, 8, (n, na)).astype(np.float64)
    queries = rng.integers(-7, 8, (nq, na)).astype(np.float64)
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = rng.integers(1, 30, nq).astype(np.int32)
    ks[2], ks[7] = 900, 1500
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    want = knn_golden(inp)

    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True,
                                        exact=False))
    got = eng.run(inp)
    assert eng.last_hetk == (8, 2)
    assert_same_results(got, want, check_dists=False)

    # Device-full keeps the device's f32 tie handling (no host repair by
    # contract), so its routing check uses continuous data where ties
    # don't arise; the tie-heavy grid above already covered run()'s
    # repair across segments.
    data_c = rng.uniform(-50, 50, (n, na))
    queries_c = rng.uniform(-50, 50, (nq, na))
    inp_c = KNNInput(Params(n, nq, na), labels, data_c, ks, queries_c)
    want_c = knn_golden(inp_c)
    eng2 = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    full = eng2.run_device_full(inp_c)
    assert eng2.last_hetk == (8, 2)
    for g, w in zip(full, want_c):
        assert g.query_id == w.query_id
        assert g.predicted_label == w.predicted_label
        assert list(g.neighbor_ids) == list(w.neighbor_ids)
        assert g.checksum() == w.checksum()


def test_sharded_extract_k_beyond_kernel_cap_routes_outliers():
    """The mesh engines route heterogeneous k too: the chunked driver
    keeps the extraction kernel for the bulk and folds the wide-k
    outliers on the SAME staged chunks (streaming mesh program), with
    golden parity on the merged results."""
    import jax
    import pytest as _pytest

    from dmlp_tpu.engine.sharded import ShardedEngine

    if len(jax.devices()) < 8:
        _pytest.skip("needs 8 devices")
    rng = np.random.default_rng(78)
    n, nq, na = 1500, 5, 3
    data = rng.uniform(-9, 9, (n, na))
    queries = rng.uniform(-9, 9, (nq, na))
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = np.array([600, 1, 1500, 520, 3], np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = ShardedEngine(EngineConfig(mode="sharded", select="extract",
                                     use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"   # bulk stayed on the kernel
    assert eng.last_hetk == (2, 3)
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_ring_hetk_routing_matches_golden():
    """Ring merge strategy serves both router segments (outlier lists
    merge by ring all-reduce too); device-full stays unrouted-compatible
    via the same segment loop."""
    import jax
    import pytest as _pytest

    from dmlp_tpu.engine.ring import RingEngine

    if len(jax.devices()) < 8:
        _pytest.skip("needs 8 devices")
    rng = np.random.default_rng(81)
    n, nq, na = 1100, 9, 4
    data = rng.uniform(0, 50, (n, na))
    queries = rng.uniform(0, 50, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 20, nq).astype(np.int32)
    ks[4], ks[8] = 700, 1100
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    want = knn_golden(inp)
    eng = RingEngine(EngineConfig(mode="ring", select="extract",
                                  use_pallas=True))
    got = eng.run(inp)
    assert eng.last_hetk == (7, 2)
    assert_same_results(got, want, check_dists=False)

    full = eng.run_device_full(inp)
    assert eng.last_hetk == (7, 2)
    for g, w in zip(full, want):
        assert g.query_id == w.query_id
        assert g.checksum() == w.checksum()


def _pad_stage(data, queries, gran_rows=256, gran_q=8):
    """Pad (data, queries) to kernel granules for DIRECT extract_topk
    calls (the engines do this via plan_chunks/QUERY_TILE)."""
    import jax.numpy as jnp

    from dmlp_tpu.engine.single import round_up
    n, na = data.shape
    nq = queries.shape[0]
    npad, qpad = round_up(n, gran_rows), round_up(nq, gran_q)
    d = np.zeros((npad, na), np.float32); d[:n] = data
    q = np.zeros((qpad, na), np.float32); q[:nq] = queries
    return jnp.asarray(d), jnp.asarray(q), n, nq


def test_extract_kernel_tie_rows_straddling_block_boundary():
    """Duplicated data rows placed EXACTLY astride an in-kernel block
    boundary (tile_n=256: rows 255/256) with k=1: the extraction must
    keep the LOWEST global position, with and without block skipping —
    the strict `m < T` tie contract the engines' repair path depends
    on. Also the chunk-boundary form: the duplicate's twin arrives in a
    later carry fold and must NOT displace the lower id."""
    import jax.numpy as jnp

    from dmlp_tpu.ops.pallas_extract import extract_topk

    rng = np.random.default_rng(5)
    n, na = 512, 4
    data = rng.uniform(-50, 50, (n, na))
    data[256] = data[255]                 # dup pair astride block boundary
    queries = np.stack([data[255], data[10]])
    d, q, n_real, _nq = _pad_stage(data, queries)
    for skip in (True, False):
        od, oi, _ = extract_topk(q, d, n_real=n_real, kc=8,
                                 interpret=True, tile_n=256,
                                 block_skip=skip)
        # row 0's best is the dup distance (0.0): slot ids must include
        # 255 — and 255 must be extracted before 256 (lowest position
        # first), so with both present the MIN of the two slots is 255.
        ids0 = set(np.asarray(oi)[0].tolist())
        assert 255 in ids0 and 256 in ids0

    # chunk-boundary ties: the same row closes chunk 1 and opens chunk 2
    d1 = rng.uniform(-50, 50, (512, na))
    d2 = rng.uniform(-50, 50, (512, na))
    d2[0] = d1[511]
    q2 = np.ascontiguousarray(d1[511][None])
    dd1, qq, _, _ = _pad_stage(d1, q2)
    dd2 = jnp.asarray(d2.astype(np.float32))
    for skip in (True, False):
        od, oi, _ = extract_topk(qq, dd1, n_real=512, kc=8,
                                 interpret=True, tile_n=256,
                                 block_skip=skip)
        od, oi, _ = extract_topk(qq, dd2, od, oi, n_real=512, id_base=512,
                                 kc=8, interpret=True, tile_n=256,
                                 block_skip=skip)
        oi_np = np.asarray(oi)[0]
        srt = oi_np[np.argsort(np.asarray(od)[0], kind="stable")]
        # both tied copies are in the top-8 (dist 0), and k=1 semantics
        # (the first report slot) keep the lower global id 511
        assert {511, 512} <= set(oi_np.tolist())
        assert min(srt[0], srt[1]) == 511


def test_extract_engine_tie_heavy_dup_rows_block_boundaries_vs_golden(
        tmp_path, monkeypatch):
    """Engine-level tie regression for block skipping: a tuner cache
    entry pins a small tile_n (many in-kernel block boundaries), the
    dataset repeats whole row-groups so tie groups straddle those
    boundaries, and the full run() must still equal the float64 golden
    model exactly — block skipping cannot silently change
    lowest-global-position tie breaking."""
    from dmlp_tpu.engine.single import resolve_kcap
    from dmlp_tpu.tune import VariantCache, clear_lookup_memo

    rng = np.random.default_rng(91)
    n_base, nq, na = 160, 14, 3
    base = rng.integers(0, 3, (n_base, na)).astype(np.float64)
    data = np.concatenate([base, base, base, base])      # 4 copies: deep ties
    n = data.shape[0]
    queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    labels = rng.integers(0, 4, n).astype(np.int32)
    # kmax stays small so kcap (40) fits the pinned tile_n — a wider k
    # would route to multipass at a different kcap and the cache entry
    # would never resolve, making the whole test vacuous.
    ks = rng.integers(1, 33, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)

    kc = resolve_kcap(EngineConfig(), int(ks.max()), "extract", 1 << 30,
                      staging="float32")
    pinned = {"tile_q": 32, "tile_n": 256, "ne": 2, "unroll": 1}
    assert kc <= pinned["tile_n"]          # the entry must be resolvable
    path = str(tmp_path / "variants.json")
    monkeypatch.setenv("DMLP_TPU_TUNE_CACHE", path)
    cache = VariantCache()
    # the engine prefers the fused megakernel (fused_topk namespace) —
    # pin BOTH namespaces so the multi-block variant drives whichever
    # kernel the dispatch resolves
    cache.put("cpu", 12800, kc, pinned, a=na)
    cache.put("cpu", 12800, kc, pinned, a=na, kernel="fused_topk")
    cache.save(path)
    clear_lookup_memo()
    from dmlp_tpu.obs import trace as obs_trace
    tracer = obs_trace.install(obs_trace.Tracer())
    try:
        eng = SingleChipEngine(EngineConfig(select="extract",
                                            use_pallas=True))
        got = eng.run(inp)
    finally:
        obs_trace.uninstall()
        clear_lookup_memo()
    assert eng._last_select == "extract"
    # prove the pinned multi-block variant actually drove the kernel
    spans = [e for e in tracer.to_dict()["traceEvents"]
             if e.get("name") == "single.enqueue_extract"]
    assert spans and spans[0]["args"]["variant"] == pinned
    assert_same_results(got, knn_golden(inp), check_dists=False)


@pytest.mark.parametrize("seed", [401, 402, 403, 404])
def test_extract_block_skip_output_identical_fuzz(seed):
    """Direct-kernel A/B over the fuzz distribution (duplicate-heavy
    grids included): block_skip on/off must be bit-identical in dists,
    ids, AND the running lists after a warm second fold — the skip gate
    may only elide rounds that would have inserted nothing."""
    import jax.numpy as jnp

    from dmlp_tpu.ops.pallas_extract import extract_topk

    inp = _case(seed)
    kc = 16
    d, q, n_real, _ = _pad_stage(inp.data_attrs, inp.query_attrs)
    outs = {}
    for skip in (True, False):
        od1, oi1, it1 = extract_topk(q, d, n_real=n_real, kc=kc,
                                     interpret=True, tile_n=256,
                                     block_skip=skip)
        od2, oi2, it2 = extract_topk(q, d, od1, oi1, n_real=n_real,
                                     id_base=d.shape[0], kc=kc,
                                     interpret=True, tile_n=256,
                                     block_skip=skip)
        outs[skip] = (np.asarray(od2), np.asarray(oi2),
                      int(np.asarray(it1).sum() + np.asarray(it2).sum()))
    assert np.array_equal(outs[True][0], outs[False][0])
    assert np.array_equal(outs[True][1], outs[False][1])
    # the gate can only REMOVE no-op rounds
    assert outs[True][2] <= outs[False][2]


def test_extract_engine_wide_k_tuned_variant():
    """k > 64 routes to the wide-list tuned variant (tq=64, ne=4,
    SWEEP_WIDEK_r04); parity must hold there too."""
    rng = np.random.default_rng(79)
    n, nq, na = 1400, 9, 5
    data = rng.uniform(-15, 15, (n, na))
    queries = rng.uniform(-15, 15, (nq, na))
    labels = rng.integers(0, 6, n).astype(np.int32)
    ks = rng.integers(100, 201, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="extract", use_pallas=True))
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert_same_results(got, knn_golden(inp), check_dists=False)
