"""Generator parity: byte-identical output vs the reference generator.

The canonical inputs are missing from the snapshot (survey §6), so seeded
regeneration IS the input protocol; this test proves our generator replays
the reference's RNG draw order exactly by running the reference script
(read-only, as an oracle) on the same arguments.
"""

import pathlib
import subprocess
import sys

import pytest

from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import parse_input_text

REFERENCE_GEN = pathlib.Path("/root/reference/generate_input.py")


@pytest.mark.skipif(not REFERENCE_GEN.exists(), reason="reference not mounted")
@pytest.mark.parametrize("seed", [42, 7])
def test_byte_identical_with_reference_generator(tmp_path, seed):
    out = tmp_path / "ref.in"
    subprocess.run(
        [sys.executable, str(REFERENCE_GEN),
         "--num_data", "50", "--num_queries", "10", "--num_attrs", "4",
         "--min", "-5", "--max", "5", "--minK", "1", "--maxK", "8",
         "--num_labels", "3", "--seed", str(seed), "--output", str(out)],
        check=True, capture_output=True)
    ours = generate_input_text(50, 10, 4, -5, 5, 1, 8, 3, seed=seed)
    assert ours == out.read_text()


def test_generated_text_parses():
    text = generate_input_text(20, 5, 3, 0, 10, 1, 5, 4, seed=1)
    inp = parse_input_text(text)
    assert inp.params.num_data == 20
    assert inp.ks.min() >= 1 and inp.ks.max() <= 5
    assert inp.labels.min() >= 0 and inp.labels.max() <= 3


def test_k_capped_by_num_data():
    text = generate_input_text(3, 5, 2, 0, 1, 1, 100, 2, seed=3)
    inp = parse_input_text(text)
    assert inp.ks.max() <= 3
