"""Compiler-sharded engine (GSPMD): parity fuzz, composition, contract.

The AutoShardedEngine expresses the chunked distance -> top-k solve as
one pure jit with pinned NamedShardings and a with_sharding_constraint
merge point — XLA's GSPMD partitioner picks the collective schedule the
hand-rolled engines (shard_map + explicit allgather/ring merge) spell
out by hand. Everything here pins the contract that makes that swap
safe:

- byte-identity to the single-chip engine and the f64 golden model on
  duplicate-heavy tie grids and k boundaries, across mesh shapes
  (including the degenerate 1x1 mesh);
- composition with the prune/precision axes resolved OUTSIDE the jit;
- the honest no-model stance (no analytic comms claim, memory model
  priced at the allgather worst case);
- the construction-time mesh-axis contract and the loud multi-host
  NotImplementedError;
- the ``auto/`` RunRecord family landing in the perf ledger gated;
- the persistent compile cache making a relaunched daemon's cold start
  strictly cheaper with a flat bucket compile count.
"""

import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.auto import AutoShardedEngine
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.io.report import format_results
from dmlp_tpu.obs import memwatch
from dmlp_tpu.obs.comms import engine_comms
from dmlp_tpu.parallel.mesh import make_mesh
from tests.test_engine_single import assert_same_results


def _case(seed: int, kmax: int = 48) -> KNNInput:
    """Duplicate-biased corpora straddling block granules (the
    test_precision generator, with k pushed to the cap boundary)."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(120, 700))
    nq = int(rng.integers(1, 32))
    na = int(rng.integers(1, 9))
    if rng.random() < 0.5:   # integer grid: exact f32 + massive ties
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    else:
        data = rng.uniform(-20, 20, (n, na))
        queries = rng.uniform(-20, 20, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, min(n, kmax) + 1, nq).astype(np.int32)
    return KNNInput(Params(n, nq, na), labels, data, ks, queries)


def _auto(mesh_shape=(4, 2), **kw) -> AutoShardedEngine:
    return AutoShardedEngine(EngineConfig(mode="auto", **kw),
                             mesh=make_mesh(mesh_shape))


# -- byte-identity fuzz -------------------------------------------------------

@pytest.mark.parametrize("seed", range(611, 619))
def test_auto_byte_identical_to_single_and_golden(seed):
    inp = _case(seed)
    got = _auto().run(inp)
    solo = SingleChipEngine(EngineConfig()).run(inp)
    gold = knn_golden(inp)
    assert format_results(got) == format_results(solo) \
        == format_results(gold)
    assert_same_results(got, gold)


@pytest.mark.parametrize("shape", [(1, 1), (2, 4), (8, 1), (1, 8)])
def test_auto_mesh_shapes_byte_identical(shape):
    """Every mesh factorization — including the degenerate 1x1 and the
    all-data / all-query extremes — resolves to the same bytes: GSPMD
    owns the schedule, never the answer."""
    inp = _case(733)
    devices = None
    if shape == (1, 1):
        devices = jax.devices()[:1]
    eng = AutoShardedEngine(EngineConfig(mode="auto"),
                            mesh=make_mesh(shape, devices=devices))
    assert format_results(eng.run(inp)) == format_results(knn_golden(inp))


def test_auto_k_boundary_tie_grid():
    """k == 1, k == n, and a duplicate group astride the shard edge:
    the merged candidate lists must keep the composite (dist asc, id
    desc) order the repair pipeline assumes."""
    rng = np.random.default_rng(91)
    n, na = 264, 3
    data = rng.integers(0, 2, (n, na)).astype(np.float64)
    data[128:144] = data[0]        # duplicate row group across shards
    queries = data[[0, 5, 130, 263]].copy()
    ks = np.array([1, n, 48, 7], np.int32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    inp = KNNInput(Params(n, 4, na), labels, data, ks, queries)
    got = _auto().run(inp)
    gold = knn_golden(inp)
    assert format_results(got) == format_results(gold)
    assert_same_results(got, gold)


def test_auto_chunked_data_block_byte_identical():
    inp = _case(645)
    eng = _auto(data_block=64)
    assert format_results(eng.run(inp)) == format_results(knn_golden(inp))


# -- composition: config axes resolved OUTSIDE the jit ------------------------

def test_auto_bf16_first_pass_byte_identical(monkeypatch):
    monkeypatch.delenv("DMLP_TPU_PRECISION", raising=False)
    inp = _case(821)
    eng_b = _auto(precision="bf16")
    eng_f = _auto(precision="f32")
    gold = knn_golden(inp)
    assert format_results(eng_b.run(inp)) == format_results(eng_f.run(inp)) \
        == format_results(gold)
    assert eng_b.last_precision["active"] == "bf16"
    assert eng_f.last_precision["active"] == "f32"


def test_auto_prune_composition_skips_blocks_and_stays_golden(monkeypatch):
    """Clustered corpus with a far band: prune on must skip blocks
    (host scan bytes drop), prune off must scan dense — both arms
    byte-identical to golden."""
    rng = np.random.default_rng(55)
    n, nq, na = 4096, 6, 3
    data = rng.uniform(0, 1, (n, na))
    data[3584:] += 500.0           # far band: whole blocks prunable
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 4, n).astype(np.int32), data,
                   rng.integers(1, 6, nq).astype(np.int32),
                   rng.uniform(0, 1, (nq, na)))
    gold = format_results(knn_golden(inp))
    pruned_arm = {}
    for prune in ("1", "0"):
        monkeypatch.setenv("DMLP_TPU_PRUNE", prune)
        eng = AutoShardedEngine(
            EngineConfig(mode="auto", data_block=512),
            mesh=make_mesh((4, 1), devices=jax.devices()[:4]))
        assert format_results(eng.run(inp)) == gold, prune
        pruned_arm[prune] = dict(eng.last_prune)
    assert pruned_arm["0"]["blocks_pruned"] == 0
    assert pruned_arm["1"]["blocks_pruned"] > 0
    assert pruned_arm["1"]["scanned_bytes"] < pruned_arm["0"]["dense_bytes"]


def test_auto_fast_mode_no_repair_paths_still_match_slow_k_order():
    """Fast (non-exact) mode routes the device-full epilogue; the
    report bytes must still match golden (device ordering is exact on
    these integer grids)."""
    rng = np.random.default_rng(71)
    n, nq, na = 300, 5, 4
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 4, n).astype(np.int32),
                   rng.integers(0, 3, (n, na)).astype(np.float64),
                   rng.integers(1, 12, nq).astype(np.int32),
                   rng.integers(0, 3, (nq, na)).astype(np.float64))
    got = _auto(exact=False).run(inp)
    gold = knn_golden(inp)
    assert format_results(got) == format_results(gold)


# -- the honest no-model stance ----------------------------------------------

def test_auto_reports_no_analytic_comms():
    assert engine_comms("gspmd", (4, 2), 8, 5) == []
    eng = _auto()
    eng.run(_case(733))
    assert eng.last_comms == []


def test_auto_memory_model_prices_allgather_worst_case():
    """The admission model must not under-budget a compiler-chosen
    schedule: gspmd merge buffers are priced at the allgather worst
    case (>= the ring model, == the allgather model)."""
    kw = dict(mesh_shape=(4, 2), shard_rows=256, na=8, monolithic=True,
              qloc=64, kcap=32)
    auto_m = memwatch.fleet_engine_model(merge="gspmd", **kw)
    ag_m = memwatch.fleet_engine_model(merge="allgather", **kw)
    ring_m = memwatch.fleet_engine_model(merge="ring", **kw)
    assert auto_m["total_bytes"] == ag_m["total_bytes"]
    assert auto_m["total_bytes"] >= ring_m["total_bytes"]


# -- construction + multi-host contract ---------------------------------------

def test_auto_rejects_mesh_without_named_axes():
    devs = np.array(jax.devices()[:2]).reshape(2, 1)
    with pytest.raises(ValueError, match="must declare axes"):
        AutoShardedEngine(EngineConfig(mode="auto"),
                          mesh=Mesh(devs, ("rows", "cols")))


def test_auto_multi_host_contract_fails_loudly():
    eng = _auto()
    with pytest.raises(NotImplementedError, match="multi-host"):
        eng.solve_global(None, None, None, None, 5)
    with pytest.raises(NotImplementedError, match="multi-host"):
        eng.solve_local_shards(None, None, None, None, 5)


def test_fleet_mesh_engine_accepts_auto_merge():
    from dmlp_tpu.fleet.mesh_engine import MeshResidentEngine
    rng = np.random.default_rng(17)
    n, na = 600, 5
    corpus = KNNInput(Params(n, 0, na),
                      rng.integers(0, 4, n).astype(np.int32),
                      rng.uniform(0, 50, (n, na)),
                      np.zeros(0, np.int32), np.zeros((0, na)))
    q = rng.uniform(0, 50, (7, na))
    ks = np.array([1, 3, 8, 12, 5, 2, 7], np.int32)
    eng = MeshResidentEngine(corpus, EngineConfig(),
                             mesh_shape=(4, 1), merge="auto")
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    inp = KNNInput(Params(n, len(ks), na), corpus.labels,
                   corpus.data_attrs, ks, q)
    want = [r.checksum() for r in knn_golden(inp)]
    assert got == want
    assert eng.bucket_stats()["merge"] == "gspmd"
    with pytest.raises(ValueError):
        MeshResidentEngine(corpus, EngineConfig(), merge="bogus")


# -- the gated auto/ ledger family --------------------------------------------

def test_auto_runrecord_lands_in_gated_auto_family(tmp_path):
    from dmlp_tpu.obs.ledger import ingest_file
    from dmlp_tpu.obs.run import RunRecord
    rec = tmp_path / "AUTO_r99.jsonl"
    RunRecord(kind="auto", tool="dmlp_tpu.bench",
              config={"config_id": 2},
              metrics={"engine_ms_auto": 100.0,
                       "engine_ms_auto_reps": [99.0, 101.0],
                       "compile_ms_auto": 400.0},
              round=99).append_jsonl(str(rec))
    entry = ingest_file(str(rec))
    assert entry["status"] == "parsed"
    series = {p["series"] for p in entry["points"]}
    assert "auto/config2/engine_ms_auto" in series
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    assert pg.gated("auto/config2/engine_ms_auto")


# -- persistent compile cache: relaunch is cheaper, compile count flat --------

def test_warm_compile_cache_relaunch_cheaper_and_count_flat(tmp_path):
    """Two serve daemons, same corpus + warm buckets, same
    ``--compile-cache`` dir: the second (warm) cold start must be
    strictly cheaper with an unchanged bucket compile count — the
    executables are reused, not rebuilt. Subprocesses, not threads:
    jax's in-process jit cache would mask the persistent layer."""
    from dmlp_tpu.fleet import harness as fh
    from dmlp_tpu.serve import client as sc
    header = {"serve_trace_schema": 1,
              "corpus": dict(num_data=200, num_queries=4, num_attrs=4,
                             min_attr=0.0, max_attr=50.0, min_k=1,
                             max_k=8, num_labels=5, seed=42)}
    corpus_path = tmp_path / "corpus.in"
    corpus_path.write_text(sc.corpus_text(header))
    ccdir = tmp_path / "compile_cache"
    out = str(tmp_path)
    colds, counts = [], []
    for gen in ("cold", "warm"):
        fp = fh.spawn_replica(str(corpus_path), out, f"cc_{gen}",
                              "8x8", batch_cap=8,
                              compile_cache=str(ccdir))
        try:
            fh.await_replica(fp)
            colds.append(fp.ready["cold_start_compile_ms"])
            counts.append(fp.ready["compile_count"])
            cli = sc.ServeClient(fp.ready["port"])
            cli.drain()
            cli.close()
            assert fp.proc.wait(timeout=120) == 0
        finally:
            fh.kill_all([fp])
    assert os.path.isdir(str(ccdir)) and os.listdir(str(ccdir)), \
        "the persistent cache directory stayed empty"
    assert counts[1] == counts[0]
    assert colds[1] < colds[0], \
        f"warm relaunch not cheaper: {colds[0]} -> {colds[1]} ms"


def test_compile_cache_flag_beats_env(monkeypatch, tmp_path):
    from dmlp_tpu.utils import compile_cache as cc
    flag_dir = tmp_path / "flagged"
    env_dir = tmp_path / "from_env"
    monkeypatch.setenv(cc.ENV_VAR, str(env_dir))
    assert cc.resolve_cache_dir(str(flag_dir)) == str(flag_dir)
    assert cc.resolve_cache_dir(None) == str(env_dir)
    monkeypatch.delenv(cc.ENV_VAR)
    assert cc.resolve_cache_dir(None) is None
