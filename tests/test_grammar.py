"""Input-grammar tests (reference common.cpp:12-55)."""

import numpy as np
import pytest

from dmlp_tpu.io.grammar import format_input, parse_input_text, parse_update


SAMPLE = """3 2 2
0 1.000000 2.000000
1 3.500000 -4.250000
0 0.000000 0.000000
Q 2 1.000000 1.000000
Q 1 -1.000000 2.500000
"""


def test_parse_basic():
    inp = parse_input_text(SAMPLE)
    assert (inp.params.num_data, inp.params.num_queries, inp.params.num_attrs) == (3, 2, 2)
    np.testing.assert_array_equal(inp.labels, [0, 1, 0])
    np.testing.assert_allclose(inp.data_attrs[1], [3.5, -4.25])
    np.testing.assert_array_equal(inp.ks, [2, 1])
    np.testing.assert_allclose(inp.query_attrs[1], [-1.0, 2.5])
    np.testing.assert_array_equal(inp.data_ids, [0, 1, 2])
    np.testing.assert_array_equal(inp.query_ids, [0, 1])


def test_roundtrip():
    inp = parse_input_text(SAMPLE)
    assert format_input(inp) == SAMPLE


def test_empty_data_line_raises():
    bad = "2 0 1\n1 0.5\n\n"
    with pytest.raises(ValueError, match="Line is empty"):
        parse_input_text(bad)


def test_malformed_query_line_raises():
    # Same error text as common.cpp:114.
    bad = "1 1 1\n0 0.5\nX 1 0.5\n"
    with pytest.raises(ValueError, match="Line is wrongly formatted"):
        parse_input_text(bad)


def test_truncated_input_raises():
    with pytest.raises(ValueError, match="record lines"):
        parse_input_text("5 5 2\n0 1.0 2.0\n")


def test_parse_update():
    u = parse_update("7 1.5 2.5 3.5")
    assert u.id == 7
    np.testing.assert_allclose(u.new_attrs, [1.5, 2.5, 3.5])


def test_header_underscore_rejected():
    """Header parity with the native parser: int('1_0') would accept PEP
    515 underscores the reference's stringstream rejects."""
    import pytest

    from dmlp_tpu.io.grammar import parse_params
    with pytest.raises(ValueError):
        parse_params("1_0 1 1")
