"""Input-grammar tests (reference common.cpp:12-55)."""

import numpy as np
import pytest

from dmlp_tpu.io.grammar import format_input, parse_input_text, parse_update


SAMPLE = """3 2 2
0 1.000000 2.000000
1 3.500000 -4.250000
0 0.000000 0.000000
Q 2 1.000000 1.000000
Q 1 -1.000000 2.500000
"""


def test_parse_basic():
    inp = parse_input_text(SAMPLE)
    assert (inp.params.num_data, inp.params.num_queries, inp.params.num_attrs) == (3, 2, 2)
    np.testing.assert_array_equal(inp.labels, [0, 1, 0])
    np.testing.assert_allclose(inp.data_attrs[1], [3.5, -4.25])
    np.testing.assert_array_equal(inp.ks, [2, 1])
    np.testing.assert_allclose(inp.query_attrs[1], [-1.0, 2.5])
    np.testing.assert_array_equal(inp.data_ids, [0, 1, 2])
    np.testing.assert_array_equal(inp.query_ids, [0, 1])


def test_roundtrip():
    inp = parse_input_text(SAMPLE)
    assert format_input(inp) == SAMPLE


def test_empty_data_line_raises():
    bad = "2 0 1\n1 0.5\n\n"
    with pytest.raises(ValueError, match="Line is empty"):
        parse_input_text(bad)


def test_malformed_query_line_raises():
    # Same error text as common.cpp:114.
    bad = "1 1 1\n0 0.5\nX 1 0.5\n"
    with pytest.raises(ValueError, match="Line is wrongly formatted"):
        parse_input_text(bad)


def test_truncated_input_raises():
    with pytest.raises(ValueError, match="record lines"):
        parse_input_text("5 5 2\n0 1.0 2.0\n")


def test_parse_update():
    u = parse_update("7 1.5 2.5 3.5")
    assert u.id == 7
    np.testing.assert_allclose(u.new_attrs, [1.5, 2.5, 3.5])


def test_header_underscore_rejected():
    """Header parity with the native parser: int('1_0') would accept PEP
    515 underscores the reference's stringstream rejects."""
    import pytest

    from dmlp_tpu.io.grammar import parse_params
    with pytest.raises(ValueError):
        parse_params("1_0 1 1")


# -- located ParseError (resilience satellite): truncated or corrupt stdin
# must name WHERE the grammar broke, never surface a raw struct/index error.

def test_parse_error_is_valueerror_with_location():
    from dmlp_tpu.io.grammar import ParseError
    e = ParseError("Line is wrongly formatted", line=3, byte_offset=17)
    assert isinstance(e, ValueError)           # historical raise type
    assert e.line == 3 and e.byte_offset == 17
    assert "line 3" in str(e) and "byte offset 17" in str(e)


def test_short_data_row_raises_parse_error_not_index_error():
    from dmlp_tpu.io.grammar import ParseError
    bad = "2 0 3\n0 1.0 2.0 3.0\n1 4.0\n"       # second row short
    with pytest.raises(ParseError, match="wrongly formatted") as ei:
        parse_input_text(bad)
    assert ei.value.line == 3
    assert ei.value.byte_offset == bad.index("1 4.0")


def test_garbage_token_locates_the_bad_line():
    from dmlp_tpu.io.grammar import ParseError
    bad = "2 1 2\n0 1.0 2.0\n1 3.0 oops\nQ 1 0.0 0.0\n"
    with pytest.raises(ParseError) as ei:
        parse_input_text(bad)
    assert ei.value.line == 3
    assert ei.value.byte_offset == bad.index("1 3.0")


def test_short_query_row_locates():
    from dmlp_tpu.io.grammar import ParseError
    bad = "1 1 2\n0 1.0 2.0\nQ 5\n"
    with pytest.raises(ParseError) as ei:
        parse_input_text(bad)
    assert ei.value.line == 3
    assert ei.value.byte_offset == bad.index("Q 5")


def test_malformed_header_raises_parse_error():
    from dmlp_tpu.io.grammar import ParseError
    for bad in ("not numbers at all\n", "3\n", ""):
        with pytest.raises(ParseError):
            parse_input_text(bad)


def test_truncated_input_reports_tail_offset():
    from dmlp_tpu.io.grammar import ParseError
    text = "5 5 2\n0 1.0 2.0\n"
    with pytest.raises(ParseError, match="truncated") as ei:
        parse_input_text(text)
    assert ei.value.byte_offset == len(text)


def test_crlf_input_offsets_are_exact():
    """Offsets come from '\n'-exact splitting: a \r\n payload keeps its
    \r inside the line (whitespace to the tokenizer), so the reported
    byte offset points at the real line start."""
    from dmlp_tpu.io.grammar import ParseError
    bad = "2 0 2\r\n0 1.0 2.0\r\n1 oops 3.0\r\n"
    with pytest.raises(ParseError) as ei:
        parse_input_text(bad)
    assert ei.value.line == 3
    assert ei.value.byte_offset == bad.index("1 oops")
