"""dmlp_tpu.check.racecheck — the runtime race sanitizer (dynamic R7).

The load-bearing property is TEETH: a seeded lock-order inversion and a
seeded blocking-call-under-lock must be caught, and a disciplined
consistent-order run must come back clean — otherwise the race-smoke
harness's empty verdict over the real daemon proves nothing.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from dmlp_tpu.check import racecheck


@pytest.fixture
def sanitizer():
    """Installed sanitizer with guaranteed restore: a leaked patch of
    threading.Lock would contaminate every later test in the
    process."""
    racecheck.install()
    racecheck.reset()
    try:
        yield racecheck
    finally:
        racecheck.reset()
        racecheck.uninstall()


def test_install_uninstall_restore_factories():
    orig_lock = threading.Lock
    orig_sleep = time.sleep
    racecheck.install()
    try:
        assert threading.Lock is not orig_lock
        assert racecheck.enabled()
        assert racecheck.install()       # idempotent
    finally:
        racecheck.uninstall()
    assert threading.Lock is orig_lock
    assert time.sleep is orig_sleep
    assert not racecheck.enabled()
    racecheck.uninstall()                # idempotent


def test_seeded_inversion_is_caught(sanitizer):
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    r = sanitizer.report()
    assert r["inversions"] == 1
    v = [x for x in r["violations"] if x["kind"] == "inversion"][0]
    assert v["held"] != v["acquiring"]
    assert "reverse_site" in v
    assert not r["ok"]


def test_cross_thread_inversion_is_caught(sanitizer):
    a = threading.Lock()
    b = threading.Lock()

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1, daemon=True)
    th.start()
    th.join()
    with b:
        with a:        # opposite order, different thread
            pass
    assert sanitizer.report()["inversions"] == 1


def test_consistent_order_and_reentrant_use_clean(sanitizer):
    a = threading.Lock()
    b = threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    r = sanitizer.report()
    assert r["ok"] and r["edges"] == 1


def test_sleep_under_lock_caught_and_clean_sleep_ignored(sanitizer):
    lk = threading.Lock()
    time.sleep(0.001)                 # no lock held: clean
    assert sanitizer.report()["ok"]
    with lk:
        time.sleep(0.001)
    r = sanitizer.report()
    assert r["blocking_under_lock"] == 1
    v = r["violations"][0]
    assert v["call"] == "time.sleep" and v["held"]


def test_thread_join_under_lock_caught(sanitizer):
    lk = threading.Lock()
    t = threading.Thread(target=lambda: None, daemon=True)
    t.start()
    with lk:
        t.join()
    r = sanitizer.report()
    assert r["blocking_under_lock"] == 1
    assert r["violations"][0]["call"] == "Thread.join"


def test_condition_wait_releases_held_tracking(sanitizer):
    """cond.wait releases the lock: a timeout-wait under the condition
    must not count as blocking-under-lock, and the handoff must
    restore the held stack for the code after wait()."""
    cond = threading.Condition()
    lk = threading.Lock()
    with cond:
        cond.wait(timeout=0.01)
        with lk:                      # still inside the cond guard
            pass
    r = sanitizer.report()
    assert r["ok"]
    assert r["edges"] == 1            # cond -> lk recorded after wait


def test_condition_producer_consumer_clean(sanitizer):
    cond = threading.Condition()
    items = []
    got = []

    def consumer():
        with cond:
            while not items:
                cond.wait(timeout=1.0)
            got.append(items.pop())

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    time.sleep(0.02)
    with cond:
        items.append(7)
        cond.notify()
    t.join(timeout=5)
    assert not t.is_alive() and got == [7]
    assert sanitizer.report()["ok"]


def test_reset_clears_graph_and_violations(sanitizer):
    a = threading.Lock()
    with a:
        time.sleep(0.001)
    assert not sanitizer.report()["ok"]
    sanitizer.reset()
    r = sanitizer.report()
    assert r["ok"] and r["edges"] == 0 and r["violations"] == []


def test_write_report_if_requested(sanitizer, tmp_path, monkeypatch):
    out = tmp_path / "RACECHECK.json"
    monkeypatch.setenv(racecheck.RACECHECK_OUT_ENV, str(out))
    a = threading.Lock()
    with a:
        pass
    path = sanitizer.write_report_if_requested()
    assert path == str(out)
    doc = json.loads(out.read_text())
    assert doc["racecheck_schema"] == 1 and doc["ok"] is True


def test_install_from_env(monkeypatch):
    monkeypatch.delenv(racecheck.RACECHECK_ENV, raising=False)
    assert racecheck.install_from_env() is False
    monkeypatch.setenv(racecheck.RACECHECK_ENV, "1")
    try:
        assert racecheck.install_from_env() is True
        assert racecheck.enabled()
    finally:
        racecheck.reset()
        racecheck.uninstall()
