"""Extraction-kernel tests (ops.pallas_extract) — interpret mode on CPU.

Kernel-level checks use integer-valued attrs so f32 distance arithmetic is
exact and any mismatch is algorithmic, not numeric (the norm-expansion
formula differs from a NumPy oracle by ULPs otherwise). Engine-level
checks run the full differential pipeline vs the float64 golden model with
select="extract", the flagship TPU path.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dmlp_tpu.config import EngineConfig  # noqa: E402
from dmlp_tpu.engine.single import SingleChipEngine  # noqa: E402
from dmlp_tpu.golden.reference import knn_golden  # noqa: E402
from dmlp_tpu.io.datagen import generate_input_text  # noqa: E402
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text  # noqa: E402
from dmlp_tpu.ops.pallas_extract import extract_topk, supports  # noqa: E402
from tests.test_engine_single import assert_same_results  # noqa: E402


def _int_attrs(rng, shape, hi=50):
    return jnp.asarray(rng.integers(0, hi, shape), jnp.float32)


def _oracle_topk_dists(q, chunks_real, kc):
    """Sorted k smallest exact squared distances per query (float64)."""
    alld = np.concatenate(chunks_real).astype(np.float64)
    tile = ((np.asarray(q, np.float64)[:, None, :] - alld[None]) ** 2).sum(-1)
    full = np.sort(tile, axis=1)
    out = np.full((tile.shape[0], kc), np.inf)
    w = min(kc, full.shape[1])
    out[:, :w] = full[:, :w]
    return out


def _check(q, chunks, nreals, kc):
    od = oi = None
    base = 0
    for d, nr in zip(chunks, nreals):
        od, oi, _ = extract_topk(q, d, od, oi, n_real=nr, id_base=base,
                                 kc=kc, interpret=True)
        base += nr
    od, oi = np.asarray(od), np.asarray(oi)
    ref = _oracle_topk_dists(q, [np.asarray(d)[:nr]
                                 for d, nr in zip(chunks, nreals)], kc)
    got = np.sort(od, axis=-1)
    assert np.array_equal(got, ref), "distances mismatch"
    # ids must reproduce their distances (and be -1 exactly on padding)
    alld = np.concatenate([np.asarray(d)[:nr]
                           for d, nr in zip(chunks, nreals)]).astype(np.float64)
    valid = oi >= 0
    assert np.array_equal(valid, np.isfinite(od))
    rec = ((np.asarray(q, np.float64)[:, None, :]
            - alld[np.clip(oi, 0, len(alld) - 1)]) ** 2).sum(-1)
    assert np.array_equal(np.where(valid, rec, np.inf),
                          np.where(valid, od.astype(np.float64), np.inf))


def test_fresh_single_chunk():
    rng = np.random.default_rng(7)
    q = _int_attrs(rng, (64, 8))
    d = _int_attrs(rng, (1024, 8))
    assert supports(64, 1024, 8, 16)
    _check(q, [d], [900], 16)


def test_carry_across_chunks():
    rng = np.random.default_rng(3)
    q = _int_attrs(rng, (16, 4))
    _check(q, [_int_attrs(rng, (1024, 4)), _int_attrs(rng, (1536, 4))],
           [1000, 1536], 24)


def test_duplicate_heavy_ties():
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.integers(0, 3, (16, 4)), jnp.float32)
    d = jnp.asarray(rng.integers(0, 3, (1024, 4)), jnp.float32)
    _check(q, [d], [1024], 24)


def test_fewer_real_rows_than_kc():
    rng = np.random.default_rng(9)
    q = _int_attrs(rng, (16, 4))
    _check(q, [_int_attrs(rng, (512, 4))], [10], 24)
    _check(q, [_int_attrs(rng, (512, 4)), _int_attrs(rng, (512, 4))],
           [10, 12], 24)


def test_supports_gates():
    assert not supports(7, 1024, 8, 16)      # queries not /8
    assert not supports(64, 1000, 8, 16)     # data not /512
    assert not supports(64, 1024, 8, 1024)   # kc wider than a block


def _engine(select="extract", **kw):
    return SingleChipEngine(EngineConfig(select=select, use_pallas=True, **kw))


def test_engine_extract_matches_golden():
    text = generate_input_text(1100, 40, 8, -10, 10, 1, 12, 5, seed=21)
    inp = parse_input_text(text)
    eng = _engine(data_block=512)
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert_same_results(got, knn_golden(inp))


def test_engine_extract_multichunk_matches_golden():
    text = generate_input_text(20000, 25, 6, -5, 5, 1, 16, 4, seed=22)
    inp = parse_input_text(text)
    eng = _engine(data_block=8192)   # 2 chunks with carry folding
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert_same_results(got, knn_golden(inp))


def test_engine_extract_duplicate_ties_fast_mode():
    # Integer grid => exact f32; fast mode (no rescore) must still match
    # via the boundary-overflow repair.
    rng = np.random.default_rng(8)
    data = rng.integers(0, 4, size=(1024, 2)).astype(np.float64)
    queries = rng.integers(0, 4, size=(24, 2)).astype(np.float64)
    labels = rng.integers(0, 3, size=1024).astype(np.int32)
    ks = rng.integers(1, 20, size=24).astype(np.int32)
    inp = KNNInput(Params(1024, 24, 2), labels, data, ks, queries)
    eng = _engine(exact=False, data_block=512)
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_engine_extract_unsupported_shape_falls_back():
    # An attr width the kernel can't tile (the VMEM bound in supports():
    # double-buffered q/d blocks at na=2000 blow the 64 MB budget), so
    # _solve_extract — and the multi-pass driver, which shares the gate —
    # must decline and the chunk-fold driver takes over; still golden.
    # (k beyond the 512 cap no longer falls back: that case now runs the
    # multi-pass extraction, test_engine_single.TestMultipassExtract.)
    text = generate_input_text(900, 6, 2000, 0, 1, 8, 16, 3, seed=5)
    inp = parse_input_text(text)
    eng = _engine()
    got = eng.run(inp)
    assert eng._last_select != "extract"
    assert_same_results(got, knn_golden(inp))


def test_engine_extract_forced_on_small_shape():
    # Explicit --select extract on a supported small shape keeps parity.
    text = generate_input_text(300, 10, 3, 0, 1, 1, 37, 3, seed=5)
    inp = parse_input_text(text)
    eng = _engine()
    got = eng.run(inp)
    assert_same_results(got, knn_golden(inp))


def test_sharded_engine_extract_matches_golden():
    """The mesh engines run the extraction kernel per shard (SMEM runtime
    scalars make per-shard id_base/n_real traced): allgather and ring
    merges, 8-device (4,2) CPU mesh, golden parity."""
    from dmlp_tpu.engine.ring import RingEngine
    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.parallel.mesh import make_mesh

    # AUTO_SELECT_THRESHOLD is per-shard; force extract explicitly.
    text = generate_input_text(2000, 48, 6, -8, 8, 1, 14, 5, seed=33)
    inp = parse_input_text(text)
    want = knn_golden(inp)
    for cls in (ShardedEngine, RingEngine):
        eng = cls(EngineConfig(mode="sharded", select="extract",
                               use_pallas=True), mesh=make_mesh())
        got = eng.run(inp)
        assert eng._last_select == "extract", cls.__name__
        assert_same_results(got, want)


def test_sharded_engine_extract_duplicate_ties():
    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(23)
    data = rng.integers(0, 3, size=(512, 3)).astype(np.float64)
    queries = rng.integers(0, 3, size=(16, 3)).astype(np.float64)
    labels = rng.integers(0, 4, size=512).astype(np.int32)
    ks = rng.integers(1, 20, size=16).astype(np.int32)
    inp = KNNInput(Params(512, 16, 3), labels, data, ks, queries)
    eng = ShardedEngine(EngineConfig(mode="sharded", select="extract",
                                     use_pallas=True), mesh=make_mesh())
    got = eng.run(inp)
    assert eng._last_select == "extract"
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_plan_shard_prefers_extract_when_supported():
    """The pre-placed-array plan (multi-host path) picks the extraction
    kernel when the feed's fixed per-shard shapes can tile it, and falls
    back gracefully when they cannot (kcap past the 512 candidate cap)."""
    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.parallel.mesh import make_mesh

    eng = ShardedEngine(EngineConfig(mode="sharded", use_pallas=True),
                        mesh=make_mesh())
    r, c = eng.mesh.devices.shape
    d = np.zeros((12800 * r, 8), np.float32)
    q = np.zeros((128 * c, 8), np.float32)
    sel, _, k = eng._plan_shard(d, q, 16, merged_width=True)
    assert sel == "extract" and k >= 16
    sel2, _, _ = eng._plan_shard(d, q, 600, merged_width=True)  # kcap > 512
    assert sel2 != "extract"


def test_contract_run_extract_path_matches_golden(tmp_path):
    """Full multi-host contract pipeline (sharded feed -> per-shard
    extraction kernel -> distributed f64 rescore -> merge) on the
    (4,2) virtual mesh, single process, golden parity."""
    import os as _os

    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.parallel.distributed import distributed_contract_run
    from dmlp_tpu.parallel.mesh import make_mesh

    text = generate_input_text(1024, 24, 5, -6, 6, 1, 12, 4, seed=41)
    path = tmp_path / "ex.txt"
    path.write_text(text)
    inp = parse_input_text(text)
    want = [r.checksum() for r in knn_golden(inp)]

    eng = ShardedEngine(EngineConfig(mode="sharded", select="extract",
                                     use_pallas=True), mesh=make_mesh())
    with open(_os.devnull, "w") as devnull:
        got = distributed_contract_run(str(path), eng,
                                       out=devnull, err=devnull)
    assert eng._last_select == "extract"
    assert [r.checksum() for r in got] == want


def _distinct_distance_input(n=600, nq=24, seed=31):
    """All (query, data) distances pairwise-distinct AND exact in f32, so
    device-full (no host repair) must match the golden model bit-for-bit
    regardless of tie policy: 1-D distinct integer attrs, queries offset by
    .25 (v1 + v2 = 2q is never solvable; every term is a small multiple of
    1/16, exactly representable)."""
    rng = np.random.default_rng(seed)
    vals = rng.permutation(n).astype(np.float64) + 1.0
    data = vals[:, None]
    queries = (rng.permutation(nq).astype(np.float64) + 0.25)[:, None]
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 17, nq).astype(np.int32)
    return KNNInput(Params(n, nq, 1), labels, data, ks, queries)


def test_engine_extract_device_full_matches_golden():
    """VERDICT r3 item 3: --device-full must run the flagship extraction
    kernel (it previously remapped to seg/topk)."""
    inp = _distinct_distance_input()
    eng = _engine()
    got = eng.run_device_full(inp)
    assert eng._last_select == "extract"
    want = knn_golden(inp)
    for g, w in zip(got, want):
        assert g.predicted_label == w.predicted_label
        assert list(g.neighbor_ids) == list(w.neighbor_ids)
        assert g.checksum() == w.checksum()


def test_sharded_device_full_extract_matches_golden():
    """Mesh device-full path honors select="extract" per shard (the merge
    re-sorts the kernel's unsorted lists before vote/report)."""
    import jax

    from dmlp_tpu.engine.sharded import RingEngine, ShardedEngine

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    inp = _distinct_distance_input(seed=32)
    want = knn_golden(inp)
    for cls, mode in ((ShardedEngine, "sharded"), (RingEngine, "ring")):
        eng = cls(EngineConfig(mode=mode, select="extract", use_pallas=True))
        got = eng.run_device_full(inp)
        assert eng._last_select == "extract", mode
        for g, w in zip(got, want):
            assert g.predicted_label == w.predicted_label, mode
            assert list(g.neighbor_ids) == list(w.neighbor_ids), mode
            assert g.checksum() == w.checksum(), mode
