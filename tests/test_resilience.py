"""Resilience subsystem: fault injection, retry/backoff, degradation
ladder, supervision — plus engine-level byte-identical recovery.

The chaos harness (tools/chaos_run.py, `make chaos-smoke`) proves the
end-to-end invariants through the real CLI; these tests pin the unit
semantics each mechanism is built from, fast enough for tier-1.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import parse_input, parse_input_text
from dmlp_tpu.io.report import format_results
from dmlp_tpu.resilience import degrade, inject, stats
from dmlp_tpu.resilience.inject import (FaultSchedule,
                                        InjectedTransientError,
                                        SimulatedResourceExhausted)
from dmlp_tpu.resilience.retry import (OperationTimeout, RetryPolicy,
                                       backoff_ms, call_with_retry,
                                       call_with_timeout, classify)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_resilience_state(monkeypatch):
    """Every test starts with no schedule installed and zero counters;
    delay faults never really sleep."""
    monkeypatch.delenv("DMLP_TPU_FAULTS", raising=False)
    monkeypatch.delenv("DMLP_TPU_RESILIENCE", raising=False)
    stats.reset()
    inject.uninstall()
    yield
    inject.uninstall()
    stats.reset()


def sched(faults, seed=0):
    return FaultSchedule.from_dict(
        {"schema": 1, "seed": seed, "faults": faults})


# -- inject: schedule validation ---------------------------------------------

def test_schedule_rejects_unknown_site():
    with pytest.raises(ValueError, match="matches no registered"):
        sched([{"site": "engine.nope", "kind": "delay"}])


def test_schedule_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        sched([{"site": "single.fetch", "kind": "explode"}])


def test_schedule_rejects_unknown_field():
    with pytest.raises(ValueError, match="unknown field"):
        sched([{"site": "single.fetch", "kind": "delay", "mss": 5}])


def test_schedule_rejects_bad_schema():
    with pytest.raises(ValueError, match="schema must be 1"):
        FaultSchedule.from_dict({"schema": 2, "faults": []})


def test_schedule_accepts_glob_sites():
    s = sched([{"site": "single.*", "kind": "delay", "times": 2}])
    inject.install(s)
    inject.fire("single.fetch")      # delay with ms=0: no-op sleep
    inject.fire("sharded.fetch")     # glob does not match
    inject.fire("single.stage_put")
    assert [e["site"] for e in s.log if e["fired"]] == \
        ["single.fetch", "single.stage_put"]


# -- inject: fire semantics --------------------------------------------------

def test_fire_noop_without_schedule():
    assert inject.fire("single.fetch") is None


def test_transient_and_oom_raise_then_exhaust():
    inject.install(sched([
        {"site": "single.fetch", "kind": "transient"},
        {"site": "single.stage_put", "kind": "oom"},
    ]))
    with pytest.raises(InjectedTransientError):
        inject.fire("single.fetch")
    with pytest.raises(SimulatedResourceExhausted,
                       match="RESOURCE_EXHAUSTED"):
        inject.fire("single.stage_put")
    # times defaults to 1: both entries are spent
    assert inject.fire("single.fetch") == []
    assert inject.fire("single.stage_put") == []
    assert stats.snapshot()["faults_injected"] == 2


def test_after_skips_first_hits():
    s = sched([{"site": "train.step", "kind": "transient", "after": 2}])
    inject.install(s)
    assert inject.fire("train.step") == []
    assert inject.fire("train.step") == []
    with pytest.raises(InjectedTransientError):
        inject.fire("train.step")


def test_when_filters_on_context():
    inject.install(sched([
        {"site": "train.step", "kind": "nan", "when": {"step": 3}}]))
    assert inject.fire("train.step", step=2) == []
    assert inject.fire("train.step", step=3) == ["nan"]
    assert inject.fire("train.step", step=3) == []   # times=1 spent


def test_prob_draws_are_seed_deterministic():
    def run(seed):
        s = sched([{"site": "train.step", "kind": "nan", "times": 50,
                    "prob": 0.5}], seed=seed)
        inject.install(s)
        for i in range(50):
            inject.fire("train.step", step=i)
        inject.uninstall()
        return [e["fired"] for e in s.log]

    a, b, c = run(7), run(7), run(8)
    assert a == b                  # same seed -> identical decisions
    assert a != c                  # different seed -> different draws
    assert any(a) and not all(a)   # prob actually probabilistic


def test_delay_uses_injectable_sleep(monkeypatch):
    slept = []
    monkeypatch.setattr(inject, "_sleep", slept.append)
    inject.install(sched([
        {"site": "single.fetch", "kind": "delay", "ms": 40}]))
    inject.fire("single.fetch")
    assert slept == [0.04]


def test_kill_switch_disables_firing(monkeypatch):
    inject.install(sched([{"site": "single.fetch", "kind": "transient"}]))
    monkeypatch.setenv("DMLP_TPU_RESILIENCE", "0")
    assert inject.fire("single.fetch") is None


def test_log_roundtrip_and_write(tmp_path):
    s = sched([{"site": "single.fetch", "kind": "delay"}])
    inject.install(s)
    inject.fire("single.fetch")
    path = str(tmp_path / "log.json")
    s.write_log(path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["seed"] == 0
    assert doc["log"][0]["site"] == "single.fetch"
    assert doc["log"][0]["fired"] is True


def test_corrupt_bytes_drops_whole_lines():
    data = b"3 1 2\n" + b"0 1.0 2.0\n" * 3 + b"Q 1 0.5 0.5\n"
    bad = inject.corrupt_bytes(data)
    assert data.startswith(bad) and bad.endswith(b"\n")
    assert bad.count(b"\n") < data.count(b"\n")   # >= 1 full line gone
    assert len(bad) <= (len(data) * 3) // 4
    # str payloads corrupt the same way; line-less input degrades empty
    assert inject.corrupt_bytes(data.decode()) == bad.decode()
    assert inject.corrupt_bytes(b"x" * 100) == b""
    assert inject.corrupt_bytes(b"") == b""


def test_corrupt_is_always_detectable():
    """Line-boundary truncation guarantees the grammar's record-count
    check raises — a corrupted payload can never silently parse."""
    from dmlp_tpu.io.grammar import ParseError
    for seed in (1, 2, 3):
        text = generate_input_text(20, 4, 3, -5, 5, 1, 4, 3, seed=seed)
        with pytest.raises(ParseError):
            parse_input_text(inject.corrupt_bytes(text))


def test_passive_not_consumed_when_raiser_fires_same_call():
    """A raising fault in the same fire() discards the actions list, so
    a passive entry fired earlier in the call rolls back (budget AND
    log) and is delivered on the retry's re-invocation instead — the
    log never claims a fault that had no effect."""
    s = sched([
        {"site": "train.step", "kind": "nan", "when": {"step": 2}},
        {"site": "train.step", "kind": "transient", "when": {"step": 2}}])
    inject.install(s)
    with pytest.raises(InjectedTransientError):
        inject.fire("train.step", step=2)
    assert [e["kind"] for e in s.log if e["fired"]] == ["transient"]
    assert inject.fire("train.step", step=2) == ["nan"]
    assert [e["kind"] for e in s.log if e["fired"]] == \
        ["transient", "nan"]


def test_passive_kind_rejected_at_non_consuming_site():
    """'corrupt'/'nan' are actions the site itself applies; scheduling
    them where fire()'s return value is discarded would count as fired
    while doing nothing — rejected at load."""
    with pytest.raises(ValueError, match="only consumed at"):
        sched([{"site": "single.fetch", "kind": "nan"}])
    with pytest.raises(ValueError, match="only consumed at"):
        sched([{"site": "*", "kind": "corrupt"}])
    sched([{"site": "io.parse", "kind": "corrupt"}])      # consumers load
    sched([{"site": "train.step", "kind": "nan"}])


# -- retry -------------------------------------------------------------------

def test_classify_three_way():
    assert classify(InjectedTransientError("x")) == "transient"
    assert classify(ConnectionError()) == "transient"
    assert classify(TimeoutError()) == "transient"
    assert classify(OperationTimeout("deadline")) == "transient"
    assert classify(RuntimeError("... UNAVAILABLE: socket closed")) == \
        "transient"
    assert classify(SimulatedResourceExhausted("x")) == "oom"
    assert classify(RuntimeError("RESOURCE_EXHAUSTED: while allocating "
                                 "1.2G")) == "oom"
    assert classify(ValueError("bad k")) == "fatal"
    assert classify(RuntimeError("plain bug")) == "fatal"


def test_backoff_deterministic_bounded_and_dethundered():
    pol = RetryPolicy(base_ms=25, cap_ms=2000, multiplier=2, jitter=0.25)
    for attempt in range(12):
        d = backoff_ms(pol, "site.a", attempt)
        raw = min(25 * 2 ** attempt, 2000)
        assert raw <= d <= raw * 1.25
        assert d == backoff_ms(pol, "site.a", attempt)   # reproducible
    # distinct sites jitter differently at the same attempt
    assert backoff_ms(pol, "site.a", 0) != backoff_ms(pol, "site.b", 0)


def test_call_with_retry_recovers_transient():
    calls = []

    def op():
        calls.append(1)
        if len(calls) < 3:
            raise InjectedTransientError("flaky")
        return "ok"

    slept = []
    assert call_with_retry(op, "t", policy=RetryPolicy(attempts=3),
                           sleep=slept.append) == "ok"
    assert len(calls) == 3 and len(slept) == 2
    assert stats.snapshot()["retries"] == 2
    assert stats.snapshot()["retry_sites"] == {"t": 2}


def test_call_with_retry_exhausts_attempts():
    def op():
        raise InjectedTransientError("always")

    with pytest.raises(InjectedTransientError):
        call_with_retry(op, "t", policy=RetryPolicy(attempts=3),
                        sleep=lambda s: None)
    assert stats.snapshot()["retries"] == 2   # attempts-1 retries


@pytest.mark.parametrize("exc", [ValueError("fatal"),
                                 SimulatedResourceExhausted("oom")])
def test_call_with_retry_propagates_nonretryable(exc):
    calls = []

    def op():
        calls.append(1)
        raise exc

    with pytest.raises(type(exc)):
        call_with_retry(op, "t", sleep=lambda s: None)
    assert len(calls) == 1                    # no second attempt
    assert stats.snapshot()["retries"] == 0


def test_retry_kill_switch(monkeypatch):
    monkeypatch.setenv("DMLP_TPU_RESILIENCE", "0")

    def op():
        raise InjectedTransientError("flaky")

    with pytest.raises(InjectedTransientError):
        call_with_retry(op, "t", sleep=lambda s: None)
    assert stats.snapshot()["retries"] == 0


def test_call_with_timeout_result_error_and_deadline():
    assert call_with_timeout(lambda: 42, 5.0, site="ok") == 42
    with pytest.raises(ValueError, match="boom"):
        call_with_timeout(lambda: (_ for _ in ()).throw(ValueError("boom")),
                          5.0, site="err")
    ev = None

    def hang():
        time.sleep(5)

    t0 = time.monotonic()
    with pytest.raises(OperationTimeout, match="exceeded"):
        call_with_timeout(hang, 0.05, site="hung")
    assert time.monotonic() - t0 < 2.0        # did not wait out the hang
    assert stats.snapshot()["timeouts"] == 1
    del ev


# -- degradation ladder ------------------------------------------------------

class _FakeEngine:
    _degrade_rung = "fused"
    last_degrade_rung = "fused"


def test_ladder_steps_down_per_oom():
    eng = _FakeEngine()
    seen = []

    def solve(inp):
        seen.append(eng._degrade_rung)
        if len(seen) < 6:
            raise SimulatedResourceExhausted("RESOURCE_EXHAUSTED")
        return "answer"

    assert degrade.run_ladder(eng, None, solve) == "answer"
    assert seen == ["lowp", "prune", "fused", "tuned", "heuristic",
                    "streaming"]
    assert eng.last_degrade_rung == "streaming"
    assert eng._degrade_rung == "fused"       # restored after the run
    assert stats.snapshot()["degradations"] == \
        ["lowp->prune", "prune->fused", "fused->tuned",
         "tuned->heuristic", "heuristic->streaming"]


def test_ladder_propagates_non_oom():
    eng = _FakeEngine()

    def solve(inp):
        raise ValueError("a real bug")

    with pytest.raises(ValueError):
        degrade.run_ladder(eng, None, solve)
    assert stats.snapshot()["degradations"] == []


def test_ladder_heuristic_rung_suppresses_tune_cache():
    from dmlp_tpu.tune import cache as tune_cache
    eng = _FakeEngine()
    seen = []

    def solve(inp):
        seen.append(tune_cache.lookup_variant(32, 1024, a=8))
        if len(seen) <= 3:
            raise SimulatedResourceExhausted("RESOURCE_EXHAUSTED")
        return "ok"

    degrade.run_ladder(eng, None, solve)
    # The prune/fused/tuned rungs may consult the cache (None here:
    # conftest pins a nonexistent path); the heuristic rung must not
    # even try.
    assert len(seen) == 4 and seen[3] is None


# -- engine-level byte-identical recovery ------------------------------------

def _small_input():
    return parse_input_text(
        generate_input_text(96, 12, 4, -5, 5, 1, 8, 3, seed=21))


def _engine():
    return SingleChipEngine(EngineConfig(data_block=32, query_block=8))


def test_engine_recovers_transients_byte_identical():
    inp = _small_input()
    golden = format_results(knn_golden(inp))
    inject.install(sched([
        {"site": "single.stage_put", "kind": "transient", "times": 2},
        {"site": "single.fetch", "kind": "transient"},
    ]))
    out = format_results(_engine().run(inp))
    assert out == golden
    snap = stats.snapshot()
    assert snap["retries"] >= 3 and snap["faults_injected"] == 3


@pytest.mark.parametrize("times,rung", [(1, "prune"),
                                        (2, "fused"),
                                        (3, "tuned"),
                                        (4, "heuristic"),
                                        (5, "streaming"),
                                        (6, "host")])
def test_engine_ladder_byte_identical(times, rung):
    inp = _small_input()
    golden = format_results(knn_golden(inp))
    inject.install(sched([
        {"site": "single.stage_put", "kind": "oom", "times": times}]))
    eng = _engine()
    assert format_results(eng.run(inp)) == golden
    assert eng.last_degrade_rung == rung
    assert len(stats.snapshot()["degradations"]) == times


def test_io_parse_corrupt_recovers():
    import io as _io
    text = generate_input_text(64, 8, 3, -5, 5, 1, 8, 3, seed=4)
    golden = parse_input_text(text)
    inject.install(sched([{"site": "io.parse", "kind": "corrupt"}]))
    inp = parse_input(_io.StringIO(text))
    np.testing.assert_array_equal(inp.data_attrs, golden.data_attrs)
    np.testing.assert_array_equal(inp.ks, golden.ks)
    assert stats.snapshot()["retries"] == 1   # re-parse was recorded


def test_resilient_get_env_deadline(monkeypatch):
    """$DMLP_TPU_OP_TIMEOUT_S bounds each readback attempt; a blown
    deadline classifies transient (retried) and bumps `timeouts`."""
    import jax.numpy as jnp

    from dmlp_tpu.engine import single as eng_single
    monkeypatch.setenv("DMLP_TPU_OP_TIMEOUT_S", "30")
    np.testing.assert_array_equal(
        eng_single.resilient_get(jnp.arange(4)), [0, 1, 2, 3])

    monkeypatch.setenv("DMLP_TPU_OP_TIMEOUT_S", "0.05")
    monkeypatch.setattr(eng_single.jax, "device_get",
                        lambda v: time.sleep(0.5))
    with pytest.raises(OperationTimeout):
        eng_single.resilient_get([1])
    assert stats.snapshot()["timeouts"] >= 1

    # With the kill switch the wrapper is a DIRECT call: no worker
    # thread, no deadline — the slow get just completes.
    monkeypatch.setenv("DMLP_TPU_RESILIENCE", "0")
    before = stats.snapshot()["timeouts"]
    eng_single.resilient_get([1])
    assert stats.snapshot()["timeouts"] == before


# -- supervision -------------------------------------------------------------

def _rank_argv(body: str):
    return [sys.executable, "-c", body]


def test_supervised_healthy_cluster_returns_rank0_output(tmp_path):
    out, err, report = __import__(
        "dmlp_tpu.resilience.supervise", fromlist=["run_supervised"]
    ).run_supervised(
        lambda attempt: [_rank_argv("print('hello from rank0')"),
                         _rank_argv("pass")],
        str(tmp_path), cluster_timeout_s=60, max_launches=1)
    assert b"hello from rank0" in out
    assert report["launches"][0]["ok"] and not report["fallback"]


def test_supervised_relaunch_then_success(tmp_path):
    from dmlp_tpu.resilience.supervise import run_supervised
    marker = tmp_path / "attempt0-failed"

    def make_cluster(attempt):
        if attempt == 0:
            return [_rank_argv(f"import pathlib, sys; "
                               f"pathlib.Path(r'{marker}').touch(); "
                               "sys.exit(3)")]
        return [_rank_argv("print('recovered')")]

    out, _, report = run_supervised(make_cluster, str(tmp_path),
                                    cluster_timeout_s=60, max_launches=2)
    assert marker.exists()
    assert b"recovered" in out
    assert [l["ok"] for l in report["launches"]] == [False, True]
    assert stats.snapshot()["restarts"] == 1


def test_supervised_exhausted_falls_back(tmp_path):
    from dmlp_tpu.resilience.supervise import run_supervised
    out, _, report = run_supervised(
        lambda attempt: [_rank_argv("import sys; sys.exit(9)")],
        str(tmp_path), cluster_timeout_s=60, max_launches=2,
        fallback=lambda: (b"degraded-answer", b""))
    assert out == b"degraded-answer"
    assert report["fallback"] is True
    assert "cluster->single-process" in stats.snapshot()["degradations"]


def test_supervised_hung_rank_hits_deadline(tmp_path):
    from dmlp_tpu.resilience.supervise import ClusterFailure, run_supervised
    with pytest.raises(ClusterFailure) as ei:
        run_supervised(
            lambda attempt: [_rank_argv("import time; time.sleep(60)")],
            str(tmp_path), cluster_timeout_s=0.5, poll_s=0.05,
            max_launches=1)
    assert "deadline" in str(ei.value)


def test_heartbeat_thread_touches_file(tmp_path):
    from dmlp_tpu.resilience.supervise import start_heartbeat
    path = str(tmp_path / "hb")
    stop = start_heartbeat(path, interval_s=0.05)
    try:
        deadline = time.monotonic() + 5
        while not os.path.exists(path) and time.monotonic() < deadline:
            time.sleep(0.02)
        assert os.path.exists(path)
    finally:
        stop.set()


# -- CLI plumbing ------------------------------------------------------------

def test_cli_faults_flag_and_fault_log(tmp_path):
    """--faults through the real engine CLI: byte-identical output,
    deterministic injection log, resilience block in the metrics."""
    text = generate_input_text(128, 12, 4, -5, 5, 1, 8, 3, seed=9)
    inp_path = tmp_path / "in.txt"
    inp_path.write_text(text)
    sched_path = tmp_path / "sched.json"
    sched_path.write_text(json.dumps({"schema": 1, "seed": 3, "faults": [
        {"site": "single.fetch", "kind": "transient"}]}))

    def run(extra, env_extra):
        env = dict(os.environ)
        env.update(env_extra)
        with open(inp_path, "rb") as f:
            p = subprocess.run(
                [sys.executable, "-m", "dmlp_tpu"] + extra, stdin=f,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
                timeout=300)
        assert p.returncode == 0, p.stderr.decode()
        return p.stdout

    golden = run([], {})
    log1 = tmp_path / "log1.json"
    metrics = tmp_path / "metrics.jsonl"
    faulted = run(["--faults", str(sched_path),
                   "--metrics", str(metrics)],
                  {"DMLP_TPU_FAULT_LOG": str(log1)})
    assert faulted == golden
    with open(metrics) as f:
        summary = [json.loads(ln) for ln in f if ln.strip()][-1]
    assert summary["resilience"]["retries"] >= 1
    assert summary["resilience"]["faults_injected"] == 1
    log2 = tmp_path / "log2.json"
    run(["--faults", str(sched_path)], {"DMLP_TPU_FAULT_LOG": str(log2)})
    assert log1.read_text() == log2.read_text()   # deterministic replay


def test_distributed_entry_faults_and_log(tmp_path):
    """--faults + $DMLP_TPU_FAULT_LOG through the distributed contract
    entry: a transient rank-solve fault recovers byte-identically and
    the injection log is persisted (regression: the entry used to skip
    the log write entirely)."""
    text = generate_input_text(96, 10, 3, -5, 5, 1, 8, 3, seed=13)
    inp_path = tmp_path / "in.txt"
    inp_path.write_text(text)
    sched_path = tmp_path / "sched.json"
    sched_path.write_text(json.dumps({"schema": 1, "seed": 4, "faults": [
        {"site": "dist.rank_solve", "kind": "transient"}]}))
    log_path = tmp_path / "dlog.json"

    def run(extra, env_extra):
        env = dict(os.environ)
        env.update(env_extra)
        p = subprocess.run(
            [sys.executable, "-m", "dmlp_tpu.distributed",
             "--input", str(inp_path)] + extra,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            timeout=300)
        assert p.returncode == 0, p.stderr.decode()
        return p.stdout

    golden = run([], {})
    faulted = run(["--faults", str(sched_path)],
                  {"DMLP_TPU_FAULT_LOG": str(log_path)})
    assert faulted == golden
    log = json.loads(log_path.read_text())["log"]
    assert [e["site"] for e in log if e["fired"]] == ["dist.rank_solve"]
