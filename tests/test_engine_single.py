"""Single-chip engine vs the float64 golden model (differential tests)."""

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text
from dmlp_tpu.io.report import format_results


def assert_same_results(got, want, check_dists=True):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g.query_id == w.query_id
        assert g.k == w.k
        assert g.predicted_label == w.predicted_label, f"query {g.query_id}"
        assert list(g.neighbor_ids) == list(w.neighbor_ids), f"query {g.query_id}"
        assert g.checksum() == w.checksum()
        if check_dists:
            np.testing.assert_allclose(g.neighbor_dists, w.neighbor_dists,
                                       rtol=1e-12)


@pytest.mark.parametrize("seed", [11, 12])
def test_exact_mode_matches_golden(seed):
    text = generate_input_text(300, 40, 8, -10, 10, 1, 12, 5, seed=seed)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(data_block=64, query_block=16))
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_exact_mode_small_blocks_edge():
    # num_data not a multiple of data_block; num_queries not a multiple of
    # query_block — exercises padding/masking everywhere.
    text = generate_input_text(37, 9, 3, 0, 1, 1, 37, 3, seed=5)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(data_block=16, query_block=4))
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_duplicate_distance_ties():
    # Integer grid attrs => many exact distance ties; f32 and f64 agree
    # exactly, so tie-breaking is what's under test.
    rng = np.random.default_rng(0)
    data = rng.integers(0, 4, size=(64, 2)).astype(np.float64)
    queries = rng.integers(0, 4, size=(16, 2)).astype(np.float64)
    labels = rng.integers(0, 3, size=64).astype(np.int32)
    ks = rng.integers(1, 20, size=16).astype(np.int32)
    inp = KNNInput(Params(64, 16, 2), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(data_block=16, query_block=8))
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_fast_mode_integer_attrs_matches_golden():
    # exact=False (no f64 rescore): with integer attrs the f32 matmul path
    # is exact, so even fast mode must reproduce the golden results.
    rng = np.random.default_rng(3)
    data = rng.integers(-8, 8, size=(50, 3)).astype(np.float64)
    queries = rng.integers(-8, 8, size=(10, 3)).astype(np.float64)
    labels = rng.integers(0, 4, size=50).astype(np.int32)
    ks = np.full(10, 7, np.int32)
    inp = KNNInput(Params(50, 10, 3), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(exact=False, data_block=16, query_block=8))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


def test_device_full_pipeline_integer_attrs():
    rng = np.random.default_rng(4)
    data = rng.integers(0, 6, size=(40, 4)).astype(np.float64)
    queries = rng.integers(0, 6, size=(12, 4)).astype(np.float64)
    labels = rng.integers(0, 5, size=40).astype(np.int32)
    ks = rng.integers(1, 9, size=12).astype(np.int32)
    inp = KNNInput(Params(40, 12, 4), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(exact=False, data_block=8, query_block=4))
    got = eng.run_device_full(inp)
    want = knn_golden(inp)
    for g, w in zip(got, want):
        assert g.predicted_label == w.predicted_label
        assert list(g.neighbor_ids) == list(w.neighbor_ids)
        assert g.checksum() == w.checksum()


def test_k_equals_num_data():
    text = generate_input_text(16, 4, 2, 0, 5, 16, 16, 2, seed=9)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(data_block=8, query_block=4))
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_k_exceeds_num_data_sentinel_padding():
    inp = KNNInput(Params(2, 1, 1),
                   np.array([1, 0], np.int32),
                   np.array([[0.0], [2.0]]),
                   np.array([5], np.int32),
                   np.array([[0.5]]))
    eng = SingleChipEngine(EngineConfig(data_block=8, query_block=8))
    got = eng.run(inp)
    assert list(got[0].neighbor_ids) == [0, 1, -1, -1, -1]
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_stdout_text_matches_golden():
    text = generate_input_text(100, 10, 4, -1, 1, 1, 8, 3, seed=21)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig())
    got = format_results(eng.run(inp))
    want = format_results(knn_golden(inp))
    assert got == want
    assert got.startswith("Query 0 checksum: ")


def test_bf16_exact_mode_matches_golden():
    """VERDICT r2 item 7: dtype=bfloat16 + exact f64 rescore must hold
    checksum parity — the coarse on-device selection is licensed by the
    margin + boundary-tie repair. On generator-style continuous data the
    repair rarely fires (0/10000 queries at the benchmark shape,
    BENCH_BF16_r04.json) and bf16 staging is 2.3x faster end-to-end, so
    dtype="auto" resolves to bf16 on TPU in exact mode; this test's
    contrived ranges exercise the repair-heavy worst case."""
    text = generate_input_text(2000, 80, 16, -50, 50, 1, 32, 6, seed=3)
    inp = parse_input_text(text)
    for select in ("topk", "seg", "extract"):
        eng = SingleChipEngine(EngineConfig(dtype="bfloat16", exact=True,
                                            select=select,
                                            use_pallas=select == "extract"))
        assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)
        assert eng._last_select == select  # no silent fallback


def test_bf16_exact_duplicate_heavy_ties():
    """bf16 + duplicates: every distance collapses into a handful of
    values, so the tie-overflow repair must fire wholesale and still
    land on golden."""
    rng = np.random.default_rng(17)
    data = rng.integers(0, 3, size=(512, 3)).astype(np.float64)
    queries = rng.integers(0, 3, size=(24, 3)).astype(np.float64)
    labels = rng.integers(0, 4, size=512).astype(np.int32)
    ks = rng.integers(1, 24, size=24).astype(np.int32)
    inp = KNNInput(Params(512, 24, 3), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(dtype="bfloat16", exact=True,
                                        select="topk"))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


def test_auto_dtype_resolution(monkeypatch):
    """dtype="auto" resolves per backend: bf16 only on TPU and only in
    exact mode (fast mode's output IS the device ordering, so the dtype
    must never change behind the caller's back)."""
    import jax

    # This CI runs on the CPU backend (conftest pins JAX_PLATFORMS=cpu).
    assert EngineConfig().resolve_dtype() == "float32"
    assert EngineConfig(dtype="bfloat16").resolve_dtype() == "bfloat16"
    assert EngineConfig(dtype="float32").resolve_dtype() == "float32"

    class _FakeTpu:
        platform = "tpu"

    monkeypatch.setattr(jax, "devices", lambda: [_FakeTpu()])
    assert EngineConfig().resolve_dtype() == "bfloat16"
    assert EngineConfig(exact=False).resolve_dtype() == "float32"
    assert EngineConfig(dtype="float32").resolve_dtype() == "float32"


def test_bf16_wide_k_eps_repair_matches_golden():
    """Regression (r4): bf16 attr rounding perturbs distances
    NON-monotonically, so a true neighbor can rank past the candidate
    horizon with no exact device tie — the old exact-equality hazard test
    missed it (0 repairs, wrong checksums at k ~ 1500). The eps-widened
    test (finalize.staging_eps) plus the k-scaled bf16 margin must catch
    and repair every such query."""
    rng = np.random.default_rng(30)
    n, nq, na = 4000, 30, 32
    data = rng.uniform(0, 100, (n, na))
    queries = rng.uniform(0, 100, (nq, na))
    labels = rng.integers(0, 10, n).astype(np.int32)
    ks = rng.integers(1400, 1601, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(dtype="bfloat16", select="topk"))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


def test_no_auto_coarsen_guard():
    """run_device_full must not let dtype="auto" stage bf16 (its output IS
    the device ordering; no rescore licenses coarsening) while an explicit
    bfloat16 request stays honored."""
    from dmlp_tpu.engine.single import no_auto_coarsen

    eng = SingleChipEngine(EngineConfig())
    eng._staging = "bfloat16"  # simulate auto -> bf16 (TPU backend)
    with no_auto_coarsen(eng):
        assert eng._staging == "float32"
    assert eng._staging == "bfloat16"

    eng2 = SingleChipEngine(EngineConfig(dtype="bfloat16"))
    with no_auto_coarsen(eng2):
        assert eng2._staging == "bfloat16"


def test_chunk_throttle_window():
    """The staging backpressure keeps at most W fold outputs pending and
    blocks oldest-first (beyond-HBM streaming: without this, the enqueue
    loop would allocate every chunk's device buffer ahead of execution)."""
    from dmlp_tpu.engine.single import ChunkThrottle

    waited = []

    class _Fake:
        def __init__(self, i):
            self.i = i

    import jax

    orig = jax.block_until_ready
    t = ChunkThrottle(window=3)
    try:
        jax.block_until_ready = lambda x: waited.append(x.i)
        for i in range(10):
            t.tick(_Fake(i))
            assert len(t._pending) <= 3
    finally:
        jax.block_until_ready = orig
    assert waited == [0, 1, 2, 3, 4, 5, 6]  # oldest-first, window kept full


@pytest.mark.parametrize("select,n", [("sort", 600), ("topk", 600),
                                      ("extract", 900)])
def test_clustered_cancellation_repair_matches_golden(select, n):
    """Regression (r4 fuzz): clustered near-duplicate points at coordinate
    scale ~5 have true distance gaps ~1e-6 but the f32 norm-expansion's
    CANCELLATION error is ~1e-5 — candidates silently reorder past the
    margin with no exact tie, and the sort path wasn't hazard-flagged at
    all. The computation term of finalize.staging_eps plus the sort-path
    flag must catch and repair every such query."""
    rng = np.random.default_rng(5152)
    nq, na = 12, 3
    centers = rng.uniform(-5, 5, (3, na))
    data = centers[rng.integers(0, 3, n)] + rng.normal(0, 1e-3, (n, na))
    queries = centers[rng.integers(0, 3, nq)] + rng.normal(0, 1e-3, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 60, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select=select,
                                        use_pallas=select == "extract"))
    got = eng.run(inp)
    assert eng.last_repairs > 0  # the hazard must actually fire here
    assert_same_results(got, knn_golden(inp), check_dists=False)


def test_clustered_cancellation_sharded_matches_golden():
    """Same regression on the mesh engine (merged-list hazard test)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from dmlp_tpu.engine.sharded import ShardedEngine

    rng = np.random.default_rng(5149)
    n, nq, na = 576, 11, 3
    centers = rng.uniform(-5, 5, (3, na))
    data = centers[rng.integers(0, 3, n)] + rng.normal(0, 1e-3, (n, na))
    queries = centers[rng.integers(0, 3, nq)] + rng.normal(0, 1e-3, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 48, nq).astype(np.int32)
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)
    eng = ShardedEngine(EngineConfig(mode="sharded", use_pallas=True))
    got = eng.run(inp)
    assert eng.last_repairs > 0  # the merged-list hazard must fire here
    assert_same_results(got, knn_golden(inp), check_dists=False)


class TestMultipassExtract:
    """VERDICT r4 item 2: all-wide-k inputs run the extraction kernel in
    floor-raised passes instead of dropping to the streaming selects."""

    def _run(self, inp):
        eng = SingleChipEngine(EngineConfig(select="extract",
                                            use_pallas=True))
        got = eng.run(inp)
        assert eng._last_select == "extract"
        assert eng.last_mp_passes >= 2
        assert_same_results(got, knn_golden(inp))
        return eng

    def test_all_wide_k_matches_golden(self):
        text = generate_input_text(3000, 8, 6, -5, 5, 1300, 1500, 4,
                                   seed=11)
        eng = self._run(parse_input_text(text))
        assert eng.last_repairs == 0  # typical data: no plateau/shortfall

    def test_k_equals_num_data_all_queries(self):
        # k legal up to num_data (generate_input.py:19) — the maximal case.
        text = generate_input_text(1600, 6, 5, -3, 3, 1600, 1600, 3, seed=5)
        self._run(parse_input_text(text))

    def test_tie_plateau_stall_repairs_exact(self):
        # Every point identical: a >512-wide tie plateau pins the floor
        # after pass 1; the stall detector must flag every query for exact
        # oracle repair (the no-progress loss mode).
        n, q, a, k = 2000, 4, 3, 1000
        lines = [f"{n} {q} {a}"]
        lines += [f"{i % 3} " + " ".join(["1.000000"] * a)
                  for i in range(n)]
        lines += [f"Q {k} " + " ".join(["2.000000"] * a) for _ in range(q)]
        inp = parse_input_text("\n".join(lines) + "\n")
        eng = self._run(inp)
        assert eng.last_repairs == q  # all stalled -> all repaired

    def test_device_full_keeps_streaming_fallback(self):
        # run_device_full has no host repair, so the multipass path (whose
        # loss modes rely on it) must not serve it.
        text = generate_input_text(2000, 8, 4, -2, 2, 900, 1000, 3, seed=3)
        inp = parse_input_text(text)
        eng = SingleChipEngine(EngineConfig(select="auto", use_pallas=True))
        got = eng.run_device_full(inp)
        assert eng._last_select != "extract"
        assert_same_results(got, knn_golden(inp), check_dists=False)

    def test_mixed_k_still_routes_hetk(self):
        # One narrow-k query keeps the router's bulk non-empty: the split
        # path (not multipass) must own mixed inputs.
        text = generate_input_text(2000, 8, 4, -2, 2, 4, 8, 3, seed=9)
        inp = parse_input_text(text)
        inp.ks[0] = 1800  # one wide outlier
        eng = SingleChipEngine(EngineConfig(select="extract",
                                            use_pallas=True))
        got = eng.run(inp)
        assert eng.last_hetk is not None
        assert getattr(eng, "_mp_hazard", None) is None
        assert_same_results(got, knn_golden(inp))


def test_auto_staging_prefers_f32_for_wide_k(monkeypatch):
    """WIDEK_MP_r05 measurement: beyond the kernel window the bf16 kcap
    margin stops clearing the bf16 eps (100% oracle-repair rate at
    204800x1024, k=4096 on v5e), so dtype="auto" must stage f32 for
    wide-k solves; explicit dtype="bfloat16" stays honored."""
    import jax.numpy as jnp

    from dmlp_tpu.engine.single import staging_for_k

    monkeypatch.setattr(EngineConfig, "resolve_dtype",
                        lambda self: "bfloat16" if self.dtype == "auto"
                        else self.dtype)
    eng = SingleChipEngine(EngineConfig(dtype="auto"))
    assert eng._staging == "bfloat16"
    with staging_for_k(eng, 512):
        assert eng._staging == "bfloat16"  # at the cap: bf16 stays
    with staging_for_k(eng, 513):
        assert eng._staging == "float32"   # beyond: auto prefers f32
        assert eng._dtype == jnp.float32
    assert eng._staging == "bfloat16"      # restored

    # explicit bf16 is the caller's choice — never overridden
    eng2 = SingleChipEngine(EngineConfig(dtype="bfloat16"))
    with staging_for_k(eng2, 4096):
        assert eng2._staging == "bfloat16"

    # end-to-end: a wide-k run under forced-bf16 auto resolution must
    # still match golden (it stages f32 internally now)
    text = generate_input_text(1400, 4, 4, -3, 3, 700, 800, 3, seed=2)
    inp = parse_input_text(text)
    eng3 = SingleChipEngine(EngineConfig(select="extract", use_pallas=True,
                                         dtype="auto"))
    assert eng3._staging == "bfloat16"
    got = eng3.run(inp)
    assert_same_results(got, knn_golden(inp))
