"""Differential tests against the ACTUAL reference oracle binaries.

These run the stripped engines from the reference checkout via Open MPI's
isolated-singleton mode (one rank, no orted — discovered in build round
5) and diff them against the golden model, pinning the measured tie
semantics (TIE_SEMANTICS_r05.json) inside the committed suite. Skipped
automatically where the reference checkout or a compatible libmpi is
absent, so the suite stays portable.
"""

import os
import subprocess

import numpy as np
import pytest

from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params, format_input, \
    parse_input_text
from dmlp_tpu.io.report import format_results

REF = os.environ.get("DMLP_REFERENCE_DIR", "/root/reference")
BENCH_1 = os.path.join(REF, "benchmarks", "bench_1")

ENV = dict(os.environ, OMPI_MCA_ess_singleton_isolated="1")


def _run_binary(bench: str, text: str) -> str:
    r = subprocess.run([os.path.join(REF, "benchmarks", bench)],
                       input=text.encode(), capture_output=True, env=ENV,
                       timeout=120)
    assert r.returncode == 0, r.stderr.decode()[-500:]
    return r.stdout.decode()


def _binary_works() -> bool:
    if not os.path.exists(BENCH_1):
        return False
    try:
        return "checksum" in _run_binary(
            "bench_1", "1 1 1\n0 1.000000\nQ 1 2.000000\n")
    except Exception:
        return False


needs_binaries = pytest.mark.skipif(
    not _binary_works(),
    reason="reference oracle binaries not runnable here")


def _lines(s: str):
    return sorted(l for l in s.splitlines() if l.strip())


@needs_binaries
@pytest.mark.parametrize("seed", [4001, 4002, 4003, 4004])
def test_golden_matches_binaries_on_adversarial_ties(seed):
    """Tie-heavy adversarial instances: golden must be checksum-identical
    to bench_1/2/3 (the measured label-free tie semantics; bench_4
    disagrees with its own siblings on ties and is excluded here —
    tools/fuzz_vs_binaries.py / TIE_SEMANTICS_r05.json)."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(10, 120))
    nq = int(rng.integers(1, 8))
    na = int(rng.integers(1, 5))
    data = rng.integers(0, 3, (n, na)).astype(np.float64)
    queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = rng.integers(1, n + 1, nq).astype(np.int32)
    inp = parse_input_text(format_input(
        KNNInput(Params(n, nq, na), labels, data, ks, queries)))
    text = format_input(inp)
    want = _lines(format_results(knn_golden(inp)))
    for bench in ("bench_1", "bench_2", "bench_3"):
        assert _lines(_run_binary(bench, text)) == want, bench


@needs_binaries
def test_engine_matches_binary_end_to_end():
    """The JAX engine itself (not just golden) vs bench_1 on a mixed
    continuous + tie input."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine

    rng = np.random.default_rng(77)
    n, nq, na = 400, 10, 4
    data = np.concatenate([rng.integers(0, 3, (200, na)).astype(np.float64),
                           rng.uniform(-5, 5, (200, na)).round(6)])
    queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, n + 1, nq).astype(np.int32)
    inp = parse_input_text(format_input(
        KNNInput(Params(n, nq, na), labels, data, ks, queries)))
    got = _lines(format_results(
        SingleChipEngine(EngineConfig()).run(inp)))
    assert got == _lines(_run_binary("bench_1", format_input(inp)))


@needs_binaries
def test_vote_tie_and_selection_tie_pins():
    """The crafted micro-inputs that measured the semantics, pinned with
    the binaries' own checksums (r5 tie-semantics experiments)."""
    # 4 identical points, k=2: selection is label-free id-desc -> ids
    # [3, 2]; vote ties 0-vs-3 -> larger label 3.
    t = "4 1 1\n1 0.000000\n3 0.000000\n3 0.000000\n0 0.000000\nQ 2 0.000000\n"
    out = _run_binary("bench_1", t).strip()
    assert out == "Query 0 checksum: 10328283706273687613"
    (r,) = knn_golden(parse_input_text(t))
    assert f"Query 0 checksum: {r.checksum()}" == out
