"""Expert-parallel MoE step vs the unsharded reference.

The ep-sharded step's loss and updated params must equal a single-device
run of the identical math (moe_reference_forward) — any dispatch-mask,
expert-slice, psum-combine, or partial-loss bug diverges from the
reference within f32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dmlp_tpu.train.experts import (build_moe_state, make_ep_mesh,
                                    make_moe_train_step,
                                    moe_reference_forward)
from dmlp_tpu.train.step import make_optimizer


def _ref_step(params, x, y, lr):
    def loss_fn(p):
        logits = moe_reference_forward(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return float(loss), new


@pytest.mark.parametrize("dp,ep", [(1, 4), (2, 2), (2, 4)])
def test_moe_step_matches_unsharded_reference(dp, ep):
    if len(jax.devices()) < dp * ep:
        pytest.skip(f"needs {dp * ep} devices")
    mesh = make_ep_mesh(dp, ep)
    d_in, hidden, ffn, n_classes, n_experts = 6, 16, 24, 4, 8
    lr = 0.05
    optimizer = make_optimizer("sgd", lr, momentum=0.0)
    state = build_moe_state(mesh, optimizer, d_in, hidden, ffn, n_classes,
                            n_experts, seed=11)
    ref_params = {k: jnp.asarray(np.asarray(v))
                  for k, v in state["params"].items()}

    rng = np.random.default_rng(1)
    batch = dp * 32
    x = rng.normal(size=(batch, d_in)).astype(np.float32)
    y = rng.integers(0, n_classes, batch).astype(np.int32)

    step = make_moe_train_step(mesh, optimizer, n_experts=n_experts,
                               n_classes=n_classes)
    state, m = step(state, jnp.asarray(x), jnp.asarray(y))

    ref_loss, ref_new = _ref_step(ref_params, jnp.asarray(x),
                                  jnp.asarray(y), lr)
    assert float(m["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for k in ref_new:
        np.testing.assert_allclose(np.asarray(state["params"][k]),
                                   np.asarray(ref_new[k]),
                                   rtol=2e-4, atol=2e-6, err_msg=k)


def test_moe_routes_to_multiple_experts_and_learns():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    mesh = make_ep_mesh(1, 4)
    optimizer = make_optimizer("sgd", 0.05, momentum=0.5)
    state = build_moe_state(mesh, optimizer, 8, 16, 32, 3, 4, seed=2)

    rng = np.random.default_rng(3)
    proj = rng.normal(size=(8, 3))
    x = rng.normal(size=(96, 8)).astype(np.float32)
    y = np.argmax(x @ proj, -1).astype(np.int32)

    # Routing actually spreads over experts (not a degenerate single one).
    ref = {k: jnp.asarray(np.asarray(v)) for k, v in state["params"].items()}
    h = jnp.asarray(x) @ ref["in_w"] + ref["in_b"]
    sel = np.asarray(jnp.argmax(h @ ref["router"], -1))
    assert len(np.unique(sel)) >= 2

    step = make_moe_train_step(mesh, optimizer, n_experts=4, n_classes=3)
    losses = []
    for _ in range(40):
        state, m = step(state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.6 * losses[0]


@pytest.mark.parametrize("dp,ep", [(1, 4), (2, 2), (2, 4)])
def test_moe_a2a_full_capacity_matches_reference(dp, ep):
    """Capacity + all-to-all dispatch with capacity >= local tokens (no
    drops) must equal the unsharded dense reference exactly — loss AND
    updated params — across dp x ep meshes."""
    from dmlp_tpu.train.experts import make_moe_a2a_train_step

    if len(jax.devices()) < dp * ep:
        pytest.skip(f"needs {dp * ep} devices")
    mesh = make_ep_mesh(dp, ep)
    d_in, hidden, ffn, n_classes, n_experts = 5, 12, 20, 3, 8
    lr = 0.05
    optimizer = make_optimizer("sgd", lr, momentum=0.0)
    state = build_moe_state(mesh, optimizer, d_in, hidden, ffn, n_classes,
                            n_experts, seed=21)
    ref_params = {k: jnp.asarray(np.asarray(v))
                  for k, v in state["params"].items()}

    rng = np.random.default_rng(7)
    bl = 16                       # tokens per (dp, ep) cell
    batch = dp * ep * bl
    x = rng.normal(size=(batch, d_in)).astype(np.float32)
    y = rng.integers(0, n_classes, batch).astype(np.int32)

    step = make_moe_a2a_train_step(mesh, optimizer, n_experts=n_experts,
                                   n_classes=n_classes, capacity=bl)
    state, m = step(state, jnp.asarray(x), jnp.asarray(y))

    ref_loss, ref_new = _ref_step(ref_params, jnp.asarray(x),
                                  jnp.asarray(y), lr)
    assert float(m["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for k in ref_new:
        np.testing.assert_allclose(np.asarray(state["params"][k]),
                                   np.asarray(ref_new[k]),
                                   rtol=2e-4, atol=2e-6, err_msg=k)


def test_moe_a2a_capacity_one_drops_to_residual():
    """capacity=1: each cell forwards at most ONE token per destination;
    the rest take the residual-only path. Checked against a NumPy
    reference that reproduces the exact routing + drop semantics."""
    from dmlp_tpu.train.experts import make_moe_a2a_train_step

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    dp, ep, bl = 1, 4, 4
    mesh = make_ep_mesh(dp, ep)
    optimizer = make_optimizer("sgd", 0.05, momentum=0.0)
    state = build_moe_state(mesh, optimizer, 5, 12, 20, 3, 8, seed=3)
    p = {k: np.asarray(v) for k, v in state["params"].items()}

    rng = np.random.default_rng(9)
    batch = dp * ep * bl
    x = rng.normal(size=(batch, 5)).astype(np.float32)
    y = rng.integers(0, 3, batch).astype(np.int32)

    # Drop-aware reference: per (dp, ep) cell (contiguous batch blocks in
    # cell row-major order), tokens ranked within their destination cell;
    # rank >= capacity -> residual only.
    capacity = 1
    e_local = p["up"].shape[0] // 1  # up is the full (E, H, F) stack here
    n_experts = p["router"].shape[1]
    e_per_cell = n_experts // ep
    # jnp for the forward pieces: a last-ulp np-vs-XLA matmul difference
    # can flip a near-tied argmax and change one token's routing.
    h_all = np.asarray(jnp.asarray(x) @ jnp.asarray(p["in_w"])
                       + jnp.asarray(p["in_b"]))
    logits = np.asarray(jnp.asarray(h_all) @ jnp.asarray(p["router"]))
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    sel = np.argmax(logits, -1)
    gate = probs[np.arange(batch), sel][:, None]
    kept = np.zeros(batch, bool)
    for cell in range(dp * ep):
        lo = cell * bl
        counts = {}
        for i in range(lo, lo + bl):
            d = sel[i] // e_per_cell
            r = counts.get(d, 0)
            counts[d] = r + 1
            kept[i] = r < capacity
    up = np.einsum("bh,ehf->ebf", h_all, p["up"])
    act = np.maximum(up, 0.0)
    down = np.einsum("ebf,efh->ebh", act, p["down"])
    eo = down[sel, np.arange(batch)] * kept[:, None]
    h_out = h_all + gate * eo
    out = h_out @ p["out_w"] + p["out_b"]
    z = out - out.max(-1, keepdims=True)
    want_ce = float(np.mean(
        np.log(np.exp(z).sum(-1)) - z[np.arange(batch), y]))

    step = make_moe_a2a_train_step(mesh, optimizer, n_experts=n_experts,
                                   n_classes=3, capacity=capacity)
    state, m = step(state, jnp.asarray(x), jnp.asarray(y))
    assert kept.sum() < batch            # the scenario really drops tokens
    assert float(m["loss"]) == pytest.approx(want_ce, rel=1e-5)

    with pytest.raises(ValueError, match="capacity"):
        make_moe_a2a_train_step(mesh, optimizer, n_experts=n_experts,
                                n_classes=3, capacity=0)
