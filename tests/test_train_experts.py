"""Expert-parallel MoE step vs the unsharded reference.

The ep-sharded step's loss and updated params must equal a single-device
run of the identical math (moe_reference_forward) — any dispatch-mask,
expert-slice, psum-combine, or partial-loss bug diverges from the
reference within f32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dmlp_tpu.train.experts import (build_moe_state, make_ep_mesh,
                                    make_moe_train_step,
                                    moe_reference_forward)
from dmlp_tpu.train.step import make_optimizer


def _ref_step(params, x, y, lr):
    def loss_fn(p):
        logits = moe_reference_forward(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return float(loss), new


@pytest.mark.parametrize("dp,ep", [(1, 4), (2, 2), (2, 4)])
def test_moe_step_matches_unsharded_reference(dp, ep):
    if len(jax.devices()) < dp * ep:
        pytest.skip(f"needs {dp * ep} devices")
    mesh = make_ep_mesh(dp, ep)
    d_in, hidden, ffn, n_classes, n_experts = 6, 16, 24, 4, 8
    lr = 0.05
    optimizer = make_optimizer("sgd", lr, momentum=0.0)
    state = build_moe_state(mesh, optimizer, d_in, hidden, ffn, n_classes,
                            n_experts, seed=11)
    ref_params = {k: jnp.asarray(np.asarray(v))
                  for k, v in state["params"].items()}

    rng = np.random.default_rng(1)
    batch = dp * 32
    x = rng.normal(size=(batch, d_in)).astype(np.float32)
    y = rng.integers(0, n_classes, batch).astype(np.int32)

    step = make_moe_train_step(mesh, optimizer, n_experts=n_experts,
                               n_classes=n_classes)
    state, m = step(state, jnp.asarray(x), jnp.asarray(y))

    ref_loss, ref_new = _ref_step(ref_params, jnp.asarray(x),
                                  jnp.asarray(y), lr)
    assert float(m["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for k in ref_new:
        np.testing.assert_allclose(np.asarray(state["params"][k]),
                                   np.asarray(ref_new[k]),
                                   rtol=2e-4, atol=2e-6, err_msg=k)


def test_moe_routes_to_multiple_experts_and_learns():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    mesh = make_ep_mesh(1, 4)
    optimizer = make_optimizer("sgd", 0.05, momentum=0.5)
    state = build_moe_state(mesh, optimizer, 8, 16, 32, 3, 4, seed=2)

    rng = np.random.default_rng(3)
    proj = rng.normal(size=(8, 3))
    x = rng.normal(size=(96, 8)).astype(np.float32)
    y = np.argmax(x @ proj, -1).astype(np.int32)

    # Routing actually spreads over experts (not a degenerate single one).
    ref = {k: jnp.asarray(np.asarray(v)) for k, v in state["params"].items()}
    h = jnp.asarray(x) @ ref["in_w"] + ref["in_b"]
    sel = np.asarray(jnp.argmax(h @ ref["router"], -1))
    assert len(np.unique(sel)) >= 2

    step = make_moe_train_step(mesh, optimizer, n_experts=4, n_classes=3)
    losses = []
    for _ in range(40):
        state, m = step(state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.6 * losses[0]
