"""Fused Pallas distance+segmin kernel vs the XLA reference ops.

On the CPU test backend the kernel runs in Pallas interpreter mode — same
kernel code, same block decomposition, so shape/indexing bugs surface here
without a TPU.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import parse_input_text
from dmlp_tpu.ops.distance import masked_pairwise_sq_l2
from dmlp_tpu.ops.pallas_distance import SEG, fused_dist_segmin


@pytest.mark.parametrize("qb,b,a", [(8, 256, 16), (16, 512, 64), (256, 1024, 8)])
def test_fused_matches_xla_ops(qb, b, a):
    rng = np.random.default_rng(qb + b)
    q = jnp.asarray(rng.uniform(-5, 5, (qb, a)), jnp.float32)
    d = jnp.asarray(rng.uniform(-5, 5, (b, a)), jnp.float32)
    ids = jnp.asarray(np.where(rng.random(b) < 0.1, -1,
                               np.arange(b)), jnp.int32)
    dist, segmin = fused_dist_segmin(q, d, ids, interpret=True)
    want = masked_pairwise_sq_l2(q, d, ids)
    np.testing.assert_allclose(np.asarray(dist), np.asarray(want),
                               rtol=1e-6, atol=1e-4)
    want_min = np.asarray(want).reshape(qb, b // SEG, SEG).min(axis=-1)
    np.testing.assert_allclose(np.asarray(segmin), want_min,
                               rtol=1e-6, atol=1e-4)


def test_fused_all_sentinels_segment():
    q = jnp.zeros((8, 4), jnp.float32)
    d = jnp.ones((256, 4), jnp.float32)
    ids = jnp.concatenate([jnp.arange(128, dtype=jnp.int32),
                           jnp.full(128, -1, jnp.int32)])
    dist, segmin = fused_dist_segmin(q, d, ids, interpret=True)
    assert np.isinf(np.asarray(dist)[:, 128:]).all()
    assert np.isinf(np.asarray(segmin)[:, 1]).all()
    assert np.isfinite(np.asarray(segmin)[:, 0]).all()


def test_engine_pallas_seg_matches_golden():
    # use_pallas + seg with the fused producer (interpreted on CPU), sized
    # so the gather/cond path actually traces (nseg=64 > S=32); full parity
    # vs the golden oracle.
    text = generate_input_text(9000, 40, 6, -5, 5, 1, 4, 4, seed=51)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(use_pallas=True, select="seg",
                                        data_block=8192, query_block=16,
                                        margin=0))
    got = eng.run(inp)
    want = knn_golden(inp)
    assert all(g.checksum() == w.checksum() for g, w in zip(got, want))


def test_supports_gates_wide_attributes():
    from dmlp_tpu.ops.pallas_distance import supports
    assert supports(1024, 8192, 64)
    assert not supports(1024, 8192, 4096)  # q/d blocks would blow VMEM
    assert not supports(1024, 8000, 64)    # not whole 128-col segments
    assert not supports(1001, 8192, 64)    # queries not padded to 8
