"""Checksum contract tests (reference common.cpp:57-71).

The hardcoded expected values were produced by compiling the reference
checksum routine (the FNV-1a fold in common.cpp:59-68) with g++ and running
it on the same inputs — see tools/verify_checksum.cpp.
"""

import numpy as np

from dmlp_tpu.io.checksum import FNV_BASIS, FNV_PRIME, fnv1a_checksum, fnv1a_checksum_batch


def cpp_reference_fold(values):
    """Literal transcription of the C++ fold for cross-checking."""
    c = FNV_BASIS
    for v in values:
        c ^= v % (1 << 64)
        c = (c * FNV_PRIME) % (1 << 64)
    return c


def test_empty_neighbors():
    assert fnv1a_checksum(3, []) == cpp_reference_fold([3])


def test_basic_fold_order_sensitive():
    a = fnv1a_checksum(1, [0, 1, 2])
    b = fnv1a_checksum(1, [2, 1, 0])
    assert a != b
    assert a == cpp_reference_fold([1, 1, 2, 3])  # ids folded as id+1


def test_sentinel_minus_one_folds_as_zero():
    # id=-1 + 1 == 0 (the sentinel distinction in common.cpp:66)
    assert fnv1a_checksum(0, [-1]) == cpp_reference_fold([0, 0])


def test_negative_label_wraps_like_cpp_cast():
    # static_cast<unsigned long long>(-1) == 2**64 - 1
    assert fnv1a_checksum(-1, []) == cpp_reference_fold([(1 << 64) - 1])


def test_matches_compiled_cpp_goldens():
    # Values printed by tools/verify_checksum.cpp built with g++ -O2.
    assert fnv1a_checksum(3, []) == 4953160058118402688
    assert fnv1a_checksum(1, [0, 1, 2]) == 11099651899989310290
    assert fnv1a_checksum(0, [-1]) == 11126445248426326267
    assert fnv1a_checksum(-1, []) == 13493579617544636084
    assert fnv1a_checksum(7, [41, 12, 3, -1, -1]) == 9584307944621426467


def test_batch_matches_scalar():
    ids = np.array([[4, 2, 9], [7, 7, 7]])
    out = fnv1a_checksum_batch([1, 2], ids, [3, 2])
    assert out[0] == fnv1a_checksum(1, [4, 2, 9])
    assert out[1] == fnv1a_checksum(2, [7, 7])
