"""Serving-fleet tests: mesh-resident engine parity, router failure
paths, open-loop pacing, scrape aggregation, trace validation, and the
fleet ledger family.

The byte-identity oracle everywhere is the float64 golden model — the
fleet layers (sharded residency, routing, retry, coalescing) must be
invisible in the response bytes.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.fleet import loadgen
from dmlp_tpu.fleet import scrape as fscrape
from dmlp_tpu.fleet.mesh_engine import MeshResidentEngine
from dmlp_tpu.fleet.router import FleetRouter
from dmlp_tpu.golden.fast import knn_golden_fast
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.obs import telemetry
from dmlp_tpu.serve import client as sc
from dmlp_tpu.serve.daemon import ServeDaemon
from dmlp_tpu.serve.engine import ResidentEngine


def make_corpus(n=600, na=5, labels=4, seed=3, spread=50.0) -> KNNInput:
    rng = np.random.default_rng(seed)
    return KNNInput(
        Params(n, 0, na),
        rng.integers(0, labels, n).astype(np.int32),
        rng.uniform(0, spread, (n, na)),
        np.zeros(0, np.int32), np.zeros((0, na)))


def solo_and_golden(corpus: KNNInput, q, ks, config=None):
    inp = KNNInput(Params(corpus.params.num_data, len(ks),
                          corpus.params.num_attrs),
                   corpus.labels, corpus.data_attrs,
                   np.asarray(ks, np.int32), np.asarray(q, np.float64))
    solo = SingleChipEngine(config or EngineConfig())
    return ([r.checksum() for r in solo.run(inp)],
            [r.checksum() for r in knn_golden_fast(inp)], solo)


def batch(corpus, nq, seed, kmax=12):
    rng = np.random.default_rng(seed)
    q = rng.uniform(0, 50, (nq, corpus.params.num_attrs))
    ks = rng.integers(1, kmax, nq).astype(np.int32)
    return q, ks


# -- mesh-resident engine ------------------------------------------------------

def test_mesh_resident_stream_path_parity_and_compile_once():
    corpus = make_corpus()
    eng = MeshResidentEngine(corpus, EngineConfig(mode="sharded"),
                             mesh_shape=(2, 1))
    eng.warmup([(4, 12), (1, 4)])
    cc = eng.compile_count
    for seed in (11, 12):
        q, ks = batch(corpus, 4, seed)
        got = [r.checksum() for r in eng.solve_batch(q, ks)]
        solo, golden, _ = solo_and_golden(corpus, q, ks)
        assert got == solo == golden
    assert eng.compile_count == cc
    assert eng.bucket_stats()["paths"]["q8k16"] == "stream"


def test_mesh_resident_extract_path_parity_vs_solo_and_golden():
    corpus = make_corpus()
    cfg = EngineConfig(mode="sharded", select="extract",
                       use_pallas=True, data_block=256)
    eng = MeshResidentEngine(corpus, cfg, mesh_shape=(2, 1))
    eng.warmup([(4, 12)])
    cc = eng.compile_count
    q, ks = batch(corpus, 4, 21)
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    solo, golden, _ = solo_and_golden(
        corpus, q, ks, EngineConfig(select="extract", use_pallas=True,
                                    data_block=256))
    assert got == solo == golden
    assert eng.compile_count == cc
    assert "extract" in eng.bucket_stats()["paths"].values()


def test_mesh_resident_ring_merge_parity():
    corpus = make_corpus()
    eng = MeshResidentEngine(corpus, EngineConfig(mode="sharded"),
                             mesh_shape=(2, 1), merge="ring")
    eng.warmup([(3, 12)])
    q, ks = batch(corpus, 3, 31)
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    _, golden, _ = solo_and_golden(corpus, q, ks)
    assert got == golden
    assert eng.bucket_stats()["merge"] == "ring"


def test_mesh_resident_ingest_routes_rows_with_zero_recompilation():
    corpus = make_corpus()
    cfg = EngineConfig(mode="sharded", select="extract",
                       use_pallas=True, data_block=256)
    eng = MeshResidentEngine(corpus, cfg, mesh_shape=(2, 1))
    eng.warmup([(4, 12)])
    cc = eng.compile_count
    rebuilds0 = eng.summary_rebuilds
    rng = np.random.default_rng(9)
    m = 7
    newl = rng.integers(0, 4, m).astype(np.int32)
    newa = rng.uniform(0, 50, (m, eng.num_attrs))
    assert eng.ingest(newl, newa) == corpus.params.num_data + m
    grown = KNNInput(
        Params(corpus.params.num_data + m, 0, corpus.params.num_attrs),
        np.concatenate([corpus.labels, newl]),
        np.vstack([corpus.data_attrs, newa]),
        np.zeros(0, np.int32), np.zeros((0, corpus.params.num_attrs)))
    q, ks = batch(corpus, 4, 41)
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    _, golden, _ = solo_and_golden(grown, q, ks)
    assert got == golden
    assert eng.compile_count == cc        # zero solve recompilation
    if eng._summ is not None:             # summaries rebuilt in place
        assert eng.summary_rebuilds > rebuilds0


def test_mesh_resident_prune_skips_chunks_and_stays_golden(monkeypatch):
    # Norm-banded corpus over multiple per-shard chunks: far bands
    # must prune (live mask drops them) with the result still golden.
    monkeypatch.setenv("DMLP_TPU_PRUNE", "1")
    rng = np.random.default_rng(5)
    # Big enough that each 2-mesh shard spans multiple extract chunks
    # (the extract chunk granule is pallas_extract.BLOCK_ROWS = 12800
    # rows, so per-(shard, chunk) blocks need > 2 * 12800 rows total).
    n, na = 26000, 4
    base = rng.uniform(0.0, 1.0, (n, na))
    scale = np.repeat([1.0, 40.0, 400.0, 4000.0], n // 4)
    attrs = base + scale[:, None]
    corpus = KNNInput(Params(n, 0, na),
                      rng.integers(0, 4, n).astype(np.int32), attrs,
                      np.zeros(0, np.int32), np.zeros((0, na)))
    cfg = EngineConfig(mode="sharded", select="extract",
                       use_pallas=True, data_block=12800)
    eng = MeshResidentEngine(corpus, cfg, mesh_shape=(2, 1))
    assert eng._nchunks > 1               # pruning needs real blocks
    eng.warmup([(2, 6)])
    q = attrs[:2] + 0.01                  # near band 0: far bands prune
    ks = np.asarray([3, 6], np.int32)
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    inp = KNNInput(Params(n, 2, na), corpus.labels, attrs, ks,
                   np.asarray(q, np.float64))
    golden = [r.checksum() for r in knn_golden_fast(inp)]
    assert got == golden
    assert eng.last_prune is not None
    assert eng.last_prune["blocks_pruned"] > 0
    assert eng.last_prune["scanned_bytes"] \
        < eng.last_prune["dense_bytes"]


def test_mesh_resident_memory_models_positive():
    corpus = make_corpus()
    eng = MeshResidentEngine(corpus, EngineConfig(mode="sharded"),
                             mesh_shape=(2, 1))
    floor = eng.resident_model_bytes()
    marginal = eng.batch_model_bytes(8, 8)
    assert floor > 0 and marginal > 0
    model = eng.mem_model(8, 8)
    assert model["per_device"] is True
    assert model["total_bytes"] >= floor


def test_mesh_resident_lazy_monolithic_invalidates_admission_floor():
    # An extract-capable config stages the monolithic layout LAZILY
    # (first stream-path bucket); admission's cached per-device floor
    # must grow with it — a stale floor would over-admit by a full
    # corpus copy per device.
    from dmlp_tpu.serve.admission import AdmissionController
    corpus = make_corpus()
    cfg = EngineConfig(mode="sharded", select="extract",
                       use_pallas=True, data_block=256)
    eng = MeshResidentEngine(corpus, cfg, mesh_shape=(2, 1))
    assert eng._mono is None
    adm = AdmissionController(eng)
    floor_before = adm._resident_model_bytes()
    eng._ensure_monolithic()
    floor_after = adm._resident_model_bytes()
    assert floor_after > floor_before
    assert floor_after - floor_before \
        >= eng._shard_rows * eng.num_attrs * 4


# -- wide-k multipass serving --------------------------------------------------

def test_resident_wide_k_routes_through_multipass_and_stays_golden():
    corpus = make_corpus(n=1408, na=4, seed=7, spread=60.0)
    cfg = EngineConfig(select="extract", use_pallas=True,
                       data_block=512)
    eng = ResidentEngine(corpus, cfg)
    eng.warmup([(2, 600)])
    cc = eng.compile_count
    assert eng.bucket_stats()["paths"]["q128k1024"] == "multipass"
    rng = np.random.default_rng(17)
    q = rng.uniform(0, 60, (2, 4))
    ks = np.asarray([520, 600], np.int32)
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    solo, golden, solo_eng = solo_and_golden(corpus, q, ks, cfg)
    assert got == solo == golden
    assert eng.last_mp_passes > 1         # the multipass driver ran
    assert solo_eng.last_mp_passes > 1    # ...and is the solo path too
    assert eng.compile_count == cc        # no per-request compiles
    # The resident multipass concat is a SECOND corpus copy on device:
    # admission's resident floor must price it once warmed (and the
    # memwatch serve model must carry the term).
    from dmlp_tpu.obs import memwatch
    from dmlp_tpu.serve.admission import AdmissionController
    assert eng._mp_full is not None
    adm = AdmissionController(eng)
    total = adm._resident_model_bytes()
    model = memwatch.model_for_engine(
        eng, KNNInput(Params(eng.n_real, 2, 4),
                      eng._host_labels[:eng.n_real],
                      eng._host_attrs[:eng.n_real], ks,
                      np.asarray(q, np.float64)))
    mp_term = model["terms"].get("multipass_resident", 0)
    assert mp_term >= eng._ex_nchunks * eng._ex_chunk_rows * 4 * 2
    assert total >= mp_term


def test_resident_wide_k_survives_ingest_invalidation():
    corpus = make_corpus(n=1408, na=4, seed=7, spread=60.0)
    cfg = EngineConfig(select="extract", use_pallas=True,
                       data_block=512)
    eng = ResidentEngine(corpus, cfg)
    eng.warmup([(2, 600)])
    cc = eng.compile_count
    rng = np.random.default_rng(23)
    newl = rng.integers(0, 4, 5).astype(np.int32)
    newa = rng.uniform(0, 60, (5, 4))
    eng.ingest(newl, newa)
    grown = KNNInput(
        Params(1408 + 5, 0, 4), np.concatenate([corpus.labels, newl]),
        np.vstack([corpus.data_attrs, newa]),
        np.zeros(0, np.int32), np.zeros((0, 4)))
    q = rng.uniform(0, 60, (2, 4))
    ks = np.asarray([520, 513], np.int32)
    got = [r.checksum() for r in eng.solve_batch(q, ks)]
    _, golden, _ = solo_and_golden(grown, q, ks)
    assert got == golden
    assert eng.compile_count == cc


# -- router --------------------------------------------------------------------

def _start_daemon(corpus, **kw):
    kw.setdefault("tick_s", 0.001)
    d = ServeDaemon(corpus, kw.pop("config", EngineConfig()), port=0,
                    **kw)
    d.start()
    return d


def _query_via(port, q, k, req_id=""):
    cli = sc.ServeClient(port)
    try:
        return cli.query(q, k=k, req_id=req_id)
    finally:
        cli.close()


def test_router_byte_identity_and_fanout_across_replicas():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(4, 8)])
    d2 = _start_daemon(corpus, warm_buckets=[(4, 8)])
    router = FleetRouter([("127.0.0.1", d1.port),
                          ("127.0.0.1", d2.port)], port=0)
    router.start()
    try:
        q, ks = batch(corpus, 4, 51, kmax=8)
        _, golden, _ = solo_and_golden(corpus, q, ks)
        for i in range(6):
            cli = sc.ServeClient(router.port)
            r = cli.query(q, ks=[int(v) for v in ks], req_id=str(i))
            cli.close()
            assert r["ok"], r
            assert r["checksums"] == golden
        st = router.stats()
        assert all(rep["requests"] > 0 for rep in st["replicas"]), st
        # Health probes are not client traffic: the per-replica counts
        # must sum to exactly the queries routed.
        assert sum(rep["requests"] for rep in st["replicas"]) == 6, st
    finally:
        router.close()
        d1.close()
        d2.close()


class _CrashingReplica:
    """Answers stats probes like a healthy daemon, then CLOSES the
    connection mid-request on any query — the crash-mid-request
    fixture (the router must classify, mark it down, and retry the
    query on a healthy replica)."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.queries_seen = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    line = conn.makefile("rb").readline()
                    doc = json.loads(line)
                    if doc.get("op") == "stats":
                        conn.sendall(json.dumps(
                            {"ok": True, "stats": {"admission":
                             {"draining": False}}}).encode() + b"\n")
                    elif doc.get("op") == "drain":
                        conn.sendall(b'{"ok": true, "draining": true}\n')
                    else:
                        self.queries_seen += 1
                        # crash mid-request: close without responding
                except (OSError, ValueError):
                    pass

    def close(self):
        self.sock.close()


def test_router_replica_crash_mid_request_bounded_retry():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    crasher = _CrashingReplica()
    router = FleetRouter([("127.0.0.1", crasher.port),
                          ("127.0.0.1", d1.port)], port=0,
                         health_interval_s=600)  # probes only at start
    router.start()
    try:
        q, ks = batch(corpus, 2, 61, kmax=8)
        _, golden, _ = solo_and_golden(corpus, q, ks)
        responses = []
        cli = sc.ServeClient(router.port)
        for i in range(6):
            responses.append(
                cli.query(q, ks=[int(v) for v in ks], req_id=str(i)))
        cli.close()
        # Exactly one response per request, every one of them correct
        # (the crash is invisible to the client).
        assert len(responses) == 6
        assert all(r["ok"] for r in responses), responses
        assert all(r["checksums"] == golden for r in responses)
        assert crasher.queries_seen >= 1   # the crasher WAS tried
        # The retried request SAYS it was retried: the envelope
        # surfaces the replica-attempt count, and only retried
        # responses carry it (single-hop relays stay byte-verbatim).
        assert any(r.get("hops", 0) >= 2 for r in responses), responses
        assert all(r["hops"] >= 2 for r in responses if "hops" in r)
        st = router.stats()
        crashed = next(rep for rep in st["replicas"]
                       if rep["replica"].endswith(str(crasher.port)))
        assert not crashed["healthy"]
        assert sum(st["retries"].values()) >= 1
    finally:
        router.close()
        d1.close()
        crasher.close()


def test_router_drain_racing_query_wave():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    d2 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    router = FleetRouter([("127.0.0.1", d1.port),
                          ("127.0.0.1", d2.port)], port=0,
                         health_interval_s=0.05)
    router.start()
    try:
        q, ks = batch(corpus, 2, 71, kmax=8)
        _, golden, _ = solo_and_golden(corpus, q, ks)
        out = [None] * 12

        def worker(i):
            cli = sc.ServeClient(router.port)
            try:
                out[i] = cli.query(q, ks=[int(v) for v in ks],
                                   req_id=str(i))
            finally:
                cli.close()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(12)]
        for t in threads[:4]:
            t.start()
        # Drain replica 1 IN THE MIDDLE of the wave (direct, not via
        # the router — replica-local shutdown).
        cli = sc.ServeClient(d1.port)
        cli.drain()
        cli.close()
        for t in threads[4:]:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # Every request got exactly one response; each is either the
        # correct answer (served or retried onto d2) — no silent drops.
        assert all(r is not None for r in out)
        assert all(r["ok"] for r in out), [r for r in out
                                           if not r["ok"]][:2]
        assert all(r["checksums"] == golden for r in out)
    finally:
        router.close()
        d1.close()
        d2.close()


def test_router_propagates_admission_shed_unretried():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 4)], max_k=4)
    d2 = _start_daemon(corpus, warm_buckets=[(2, 4)], max_k=4)
    router = FleetRouter([("127.0.0.1", d1.port),
                          ("127.0.0.1", d2.port)], port=0)
    router.start()
    try:
        q, _ = batch(corpus, 2, 81, kmax=4)
        r = _query_via(router.port, q, k=9)
        assert not r["ok"]
        assert "rejected" in r["error"] and "k_too_large" in r["error"]
        st = router.stats()
        # An admission shed is explicit backpressure: propagated, not
        # retried onto the other replica.
        assert sum(st["retries"].values()) == 0, st["retries"]
        assert st["rejected"].get("admission", 0) >= 1
        ok = _query_via(router.port, q, k=3)
        assert ok["ok"]
    finally:
        router.close()
        d1.close()
        d2.close()


def test_router_ingest_fans_out_to_every_replica():
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    d2 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    router = FleetRouter([("127.0.0.1", d1.port),
                          ("127.0.0.1", d2.port)], port=0)
    router.start()
    try:
        rng = np.random.default_rng(13)
        m = 5
        newl = rng.integers(0, 4, m).astype(np.int32)
        newa = rng.uniform(0, 50, (m, corpus.params.num_attrs))
        cli = sc.ServeClient(router.port)
        r = cli.ingest([int(v) for v in newl], newa)
        cli.close()
        assert r["ok"] and r["corpus_rows"] == corpus.params.num_data + m
        for d in (d1, d2):
            assert d.engine.n_real == corpus.params.num_data + m
        grown = KNNInput(
            Params(corpus.params.num_data + m, 0,
                   corpus.params.num_attrs),
            np.concatenate([corpus.labels, newl]),
            np.vstack([corpus.data_attrs, newa]),
            np.zeros(0, np.int32),
            np.zeros((0, corpus.params.num_attrs)))
        q, ks = batch(corpus, 2, 91, kmax=8)
        _, golden, _ = solo_and_golden(grown, q, ks)
        for _ in range(4):   # both replicas see post-ingest queries
            r = _query_via(router.port, q, k=int(ks[0]))
            assert r["ok"]
        cli = sc.ServeClient(router.port)
        r = cli.query(q, ks=[int(v) for v in ks])
        cli.close()
        assert r["checksums"] == golden
    finally:
        router.close()
        d1.close()
        d2.close()


# -- open-loop paced replay ----------------------------------------------------

def test_open_loop_replay_fires_on_schedule_and_measures_queue_delay():
    corpus = make_corpus()
    d = _start_daemon(corpus, warm_buckets=[(2, 8), (1, 8)])
    try:
        header = {"serve_trace_schema": 1, "corpus": {
            "num_data": corpus.params.num_data, "num_attrs":
            corpus.params.num_attrs, "min_attr": 0.0, "max_attr": 50.0,
            "num_labels": 4}}
        reqs = [{"t_ms": i * 40, "nq": 1 + (i % 2), "k": 5,
                 "seed": 500 + i} for i in range(6)]
        t0 = time.monotonic()
        res = sc.replay_open_loop(d.port, header, reqs, speed=1.0)
        span = time.monotonic() - t0
        assert all(r.get("ok") for r in res), res
        assert all("client_ms" in r and "lag_ms" in r for r in res)
        # Open-loop pacing: the replay takes at least the trace span
        # (200 ms at speed 1), and speed=4 compresses it.
        assert span >= 0.2
        golden = sc.golden_reference(corpus, header, reqs)
        assert [r["checksums"] for r in res] == golden
    finally:
        d.close()


def test_loadgen_levels_emit_gated_fleet_series(tmp_path):
    corpus = make_corpus()
    d = _start_daemon(corpus, warm_buckets=[(2, 8), (1, 8)])
    try:
        header = {"serve_trace_schema": 1, "corpus": {
            "num_data": corpus.params.num_data, "num_attrs":
            corpus.params.num_attrs, "min_attr": 0.0, "max_attr": 50.0,
            "num_labels": 4}}
        reqs = [{"t_ms": i * 20, "nq": 1, "k": 5, "seed": 600 + i}
                for i in range(5)]
        recs = loadgen.run_levels(d.port, header, reqs,
                                  speeds=[2.0, 4.0], reps=2,
                                  replicas=1, trace="unit")
        assert len(recs) == 2
        path = tmp_path / "FLEET_r99.jsonl"
        for rec in recs:
            assert rec.metrics["errors"] == 0
            assert rec.metrics["p99_ms"] > 0
            assert len(rec.metrics["p99_ms_reps"]) == 2
            rec.append_jsonl(str(path))
        from dmlp_tpu.obs.ledger import ingest_file
        entry = ingest_file(str(path))
        assert entry["status"] == "parsed"
        series = {p["series"] for p in entry["points"]}
        assert "fleet/x2/p99_ms" in series
        assert "fleet/x4/p99_ms" in series
        p99 = next(p for p in entry["points"]
                   if p["series"] == "fleet/x2/p99_ms")
        assert p99["better"] == "lower"
        assert p99["round"] == 99
        qps = next(p for p in entry["points"]
                   if p["series"] == "fleet/x2/offered_qps")
        assert qps["better"] == "higher"
    finally:
        d.close()


# -- scrape aggregation --------------------------------------------------------

def _registry_with(prefix_counts):
    reg = telemetry.Registry()
    for name, count in prefix_counts.items():
        reg.counter(name).inc(count)
    return reg


def test_scrape_merge_sums_counters_and_buckets_valid():
    from dmlp_tpu.obs.telemetry import validate_openmetrics
    r1 = telemetry.Registry()
    r2 = telemetry.Registry()
    for reg, base in ((r1, 3), (r2, 5)):
        reg.counter("serve.requests_completed").inc(base)
        reg.counter("serve.rejected").inc(2, label="memory")
        reg.gauge("serve.corpus_rows").set(100 * base)
        h = reg.histogram("serve.request_latency_ms", unit="ms")
        for v in (base, base * 10, base * 100):
            h.observe(v)
    merged, problems = fscrape.merge_expositions(
        [r1.to_openmetrics(), r2.to_openmetrics()], ["a", "b"])
    assert problems == []
    assert validate_openmetrics(merged) == []
    lines = merged.splitlines()
    total = next(ln for ln in lines
                 if ln.startswith("serve_requests_completed_total "))
    assert float(total.split()[-1]) == 8.0
    lab = next(ln for ln in lines
               if ln.startswith('serve_rejected_total{key="memory"}'))
    assert float(lab.split()[-1]) == 4.0
    count = next(ln for ln in lines
                 if ln.startswith("serve_request_latency_ms_count"))
    assert int(count.split()[-1]) == 6
    # Gauges stay per-replica.
    assert 'serve_corpus_rows{replica="a"} 300' in merged
    assert 'serve_corpus_rows{replica="b"} 500' in merged


def test_scrape_merge_histogram_bucketwise_not_concatenated():
    r1 = telemetry.Registry()
    r2 = telemetry.Registry()
    r1.histogram("x.ms").observe(1.0)
    r2.histogram("x.ms").observe(1.0)
    merged, _ = fscrape.merge_expositions(
        [r1.to_openmetrics(), r2.to_openmetrics()])
    # Same value in both replicas -> ONE bucket line carrying count 2,
    # not two conflicting cumulative lines.
    bucket_lines = [ln for ln in merged.splitlines()
                    if ln.startswith("x_ms_bucket") and "+Inf" not in ln]
    assert len(bucket_lines) == 1, merged
    assert bucket_lines[0].endswith(" 2")


def test_fleet_view_degrades_on_unreachable_replica(tmp_path):
    reg = telemetry.Registry()
    reg.counter("serve.requests_completed").inc(4)
    snap = tmp_path / "a.prom"
    snap.write_text(reg.to_openmetrics())
    merged, problems = fscrape.fleet_view(
        [str(snap), str(tmp_path / "missing.prom")], ["a", "b"])
    assert "serve_requests_completed_total 4" in merged
    assert any("unreachable" in p for p in problems)


# -- trace validation ----------------------------------------------------------

def test_committed_trace2_is_valid_and_bursty():
    header, reqs = sc.load_trace("inputs/serve_trace2.jsonl")
    assert sc.validate_trace(header, reqs) == []
    ts = [r["t_ms"] for r in reqs]
    assert ts == sorted(ts)
    # Bursts: several requests sharing a fire offset.
    from collections import Counter
    assert Counter(ts).most_common(1)[0][1] >= 2
    # Bucket-boundary straddling on both axes.
    nqs = {r["nq"] for r in reqs}
    assert {7, 8, 9} <= nqs and {15, 16, 17} <= nqs


def test_load_trace_rejects_non_monotonic_offsets(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text(
        json.dumps({"serve_trace_schema": 1, "corpus": {
            "num_data": 10, "num_attrs": 2, "min_attr": 0.0,
            "max_attr": 1.0, "num_labels": 2}}) + "\n"
        + '{"t_ms": 5, "nq": 1, "k": 1, "seed": 1}\n'
        + '{"t_ms": 3, "nq": 1, "k": 1, "seed": 2}\n')
    with pytest.raises(ValueError, match="monotonic"):
        sc.load_trace(str(path))


def test_validate_trace_field_checks():
    header = {"serve_trace_schema": 1, "corpus": {
        "num_data": 10, "num_attrs": 2, "min_attr": 0.0,
        "max_attr": 1.0, "num_labels": 2}}
    assert sc.validate_trace(header, [{"nq": 1, "k": 1, "seed": 0}]) \
        == []
    assert sc.validate_trace(header, [{"nq": 1, "seed": 0}])
    assert sc.validate_trace(header, [{"nq": 0, "k": 1, "seed": 0}])
    assert sc.validate_trace(header, [{"nq": 1, "k": True, "seed": 0}])
    assert sc.validate_trace(
        header, [{"nq": 1, "k": 1, "seed": 0, "t_ms": -1}])
    # A non-list "ks" is a reported problem, never a TypeError crash.
    assert sc.validate_trace(header, [{"nq": 1, "ks": 5, "seed": 0}])


# -- daemon integration (mesh replica behind the real daemon) ------------------

def test_daemon_with_mesh_engine_end_to_end():
    corpus = make_corpus()
    d = ServeDaemon(corpus, EngineConfig(), port=0, tick_s=0.001,
                    warm_buckets=[(2, 8)], mesh_shape=(2, 1))
    d.start()
    try:
        assert isinstance(d.engine, MeshResidentEngine)
        q, ks = batch(corpus, 2, 101, kmax=8)
        _, golden, _ = solo_and_golden(corpus, q, ks)
        cli = sc.ServeClient(d.port)
        r = cli.query(q, ks=[int(v) for v in ks])
        stats = cli.stats()["stats"]
        cli.close()
        assert r["ok"] and r["checksums"] == golden
        assert stats["engine"]["mesh"] == [2, 1]
        rec = d.snapshot_record()
        assert rec.config["mode"] == "mesh_resident"
    finally:
        d.close()
