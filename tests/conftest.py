"""Test environment bootstrap: force a virtual 8-device CPU platform.

Multi-chip tests run on 8 virtual CPU devices (survey §4 implication) — the
sharded/ring engines are validated exactly as they would run on a TPU slice.

This container routes JAX to a tunneled TPU via an ``axon`` sitecustomize
hook that registers an extra PJRT backend factory at interpreter start;
``xla_bridge.backends()`` would then block dialing the TPU tunnel even with
JAX_PLATFORMS=cpu. Tests must never touch the real chip, so the factory is
dropped here, before any backend is initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The suite must be hermetic w.r.t. the autotuner's variant cache: with
# the default path a developer who ever ran `python -m dmlp_tpu.tune` on
# this machine would silently flip every extract test onto their swept
# variants (~/.cache/dmlp_tpu/extract_variants.json). Point the lookup
# at a path that cannot exist; tests that exercise the cache override
# this per-test (monkeypatch.setenv + tune.clear_lookup_memo).
os.environ["DMLP_TPU_TUNE_CACHE"] = os.path.join(
    os.sep, "nonexistent", "dmlp-tpu-test-tune-cache.json")

# Same hermeticity for the static-analysis fingerprint cache
# (dmlp_tpu.check.cache): tests that shell out to `python -m
# dmlp_tpu.check` must neither read a developer's warm ~/.cache verdict
# nor pollute it with fixture-tree entries. Content-hash keying makes
# cross-test sharing of this scratch dir safe.
import tempfile  # noqa: E402

os.environ["DMLP_TPU_CHECK_CACHE"] = os.path.join(
    tempfile.gettempdir(), "dmlp-tpu-test-check-cache")

# The hook may have latched jax_platforms=axon into jax.config before this
# file ran; both the config and the factory must go.
from dmlp_tpu.utils.platform import honor_cpu_request  # noqa: E402

honor_cpu_request()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (tier-1 runs -m 'not slow')")
