"""Sharded/ring engines on the virtual 8-device CPU mesh vs the golden model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.sharded import RingEngine, ShardedEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text
from dmlp_tpu.parallel.mesh import balanced_dims, make_mesh

from test_engine_single import assert_same_results


def needs_devices(n):
    return pytest.mark.skipif(len(jax.devices()) < n,
                              reason=f"needs {n} devices")


def test_balanced_dims():
    assert balanced_dims(8) == (4, 2)
    assert balanced_dims(24) == (6, 4)
    assert balanced_dims(1) == (1, 1)
    assert balanced_dims(7) == (7, 1)


@needs_devices(8)
@pytest.mark.parametrize("shape", [(4, 2), (2, 4), (8, 1), (1, 8)])
def test_sharded_matches_golden(shape):
    text = generate_input_text(230, 33, 6, -5, 5, 1, 11, 4, seed=17)
    inp = parse_input_text(text)
    eng = ShardedEngine(EngineConfig(mode="sharded", data_block=16),
                        mesh=make_mesh(shape))
    assert_same_results(eng.run(inp), knn_golden(inp))


@needs_devices(8)
def test_ring_matches_golden_and_allgather():
    text = generate_input_text(150, 21, 5, -2, 2, 1, 9, 3, seed=23)
    inp = parse_input_text(text)
    ring = RingEngine(EngineConfig(mode="ring", data_block=8),
                      mesh=make_mesh((4, 2)))
    got = ring.run(inp)
    assert_same_results(got, knn_golden(inp))
    ag = ShardedEngine(EngineConfig(mode="sharded", data_block=8),
                       mesh=make_mesh((4, 2)))
    assert_same_results(got, ag.run(inp))


@needs_devices(8)
def test_sharded_tiny_uneven_input():
    # num_data < number of data shards exercises all-sentinel shards.
    text = generate_input_text(3, 5, 2, 0, 1, 1, 3, 2, seed=4)
    inp = parse_input_text(text)
    for cls, mode in ((ShardedEngine, "sharded"), (RingEngine, "ring")):
        eng = cls(EngineConfig(mode=mode), mesh=make_mesh((4, 2)))
        assert_same_results(eng.run(inp), knn_golden(inp))


@needs_devices(8)
def test_sharded_ties_integer_attrs_fast_mode():
    rng = np.random.default_rng(7)
    data = rng.integers(0, 4, size=(64, 3)).astype(np.float64)
    queries = rng.integers(0, 4, size=(16, 3)).astype(np.float64)
    labels = rng.integers(0, 3, size=64).astype(np.int32)
    ks = rng.integers(1, 20, size=16).astype(np.int32)
    inp = KNNInput(Params(64, 16, 3), labels, data, ks, queries)
    for cls in (ShardedEngine, RingEngine):
        eng = cls(EngineConfig(mode="sharded" if cls is ShardedEngine else "ring",
                               exact=False, data_block=8),
                  mesh=make_mesh((4, 2)))
        assert_same_results(eng.run(inp), knn_golden(inp), check_dists=False)


def test_sharded_single_device_mesh():
    text = generate_input_text(40, 6, 3, 0, 1, 1, 5, 2, seed=6)
    inp = parse_input_text(text)
    eng = ShardedEngine(EngineConfig(mode="sharded"),
                        mesh=make_mesh((1, 1), devices=jax.devices()[:1]))
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_sharded_device_full_matches_golden():
    """VERDICT r1 missing item 5: device-side vote + report for the mesh
    engines, on the 8-virtual-device mesh, integer attrs (f32-safe)."""
    rng = np.random.default_rng(11)
    data = rng.integers(0, 7, size=(96, 4)).astype(np.float64)
    queries = rng.integers(0, 7, size=(24, 4)).astype(np.float64)
    labels = rng.integers(0, 5, size=96).astype(np.int32)
    ks = rng.integers(1, 9, size=24).astype(np.int32)
    inp = KNNInput(Params(96, 24, 4), labels, data, ks, queries)
    want = knn_golden(inp)
    for cls, mode in ((ShardedEngine, "sharded"), (RingEngine, "ring")):
        eng = cls(EngineConfig(mode=mode, exact=False, data_block=8,
                               query_block=8))
        got = eng.run_device_full(inp)
        for g, w in zip(got, want):
            assert g.predicted_label == w.predicted_label, mode
            assert list(g.neighbor_ids) == list(w.neighbor_ids), mode
            assert g.checksum() == w.checksum(), mode


@needs_devices(8)
def test_sharded_chunked_extract_multichunk_matches_golden():
    """VERDICT r3 item 1: the pipelined chunked mesh driver — per-shard
    rows split across multiple staged chunks with carry folding, merged
    across the data axis — must match the golden model exactly. The
    data_block=12800 hint forces 2 chunks per shard (shard_rows 25600,
    chunk_rows 12800 at the extract granule), so the non-fresh carry
    branch of the fold program is really exercised."""
    text = generate_input_text(30000, 17, 5, -8, 8, 1, 13, 4, seed=29)
    inp = parse_input_text(text)
    for cls, mode in ((ShardedEngine, "sharded"), (RingEngine, "ring")):
        eng = cls(EngineConfig(mode=mode, select="extract", use_pallas=True,
                               data_block=12800),
                  mesh=make_mesh((2, 4)))
        got = eng.run(inp)
        assert eng._last_select == "extract", mode
        assert_same_results(got, knn_golden(inp))


@needs_devices(8)
def test_sharded_chunked_extract_overshoot_shard_boundary():
    """plan_chunks can overshoot (nchunks * chunk_rows > shard_rows):
    n=120000, r=2 -> shard_rows 64000, data_block=25600 -> 3 chunks of
    25600 = 76800 staged rows per shard. The last chunk's tail crosses
    into the next shard's id range; an uncapped fold would stage those
    rows TWICE and the merge would report duplicate neighbor ids. Exact
    golden parity proves the cap (both host- and device-side) holds."""
    text = generate_input_text(120000, 9, 3, -6, 6, 1, 11, 3, seed=33)
    inp = parse_input_text(text)
    eng = ShardedEngine(EngineConfig(mode="sharded", select="extract",
                                     use_pallas=True, data_block=25600),
                        mesh=make_mesh((2, 4)))
    got = eng.run(inp)
    assert eng._last_select == "extract"
    # The overshoot plan must really have been exercised.
    from dmlp_tpu.engine.single import plan_chunks
    shard_rows, nchunks, chunk_rows = plan_chunks(60000, 12800, 25600)
    assert nchunks * chunk_rows > shard_rows
    assert_same_results(got, knn_golden(inp))


@needs_devices(8)
def test_sharded_device_full_stages_swapped_dtype(monkeypatch):
    """ADVICE r4 (medium): no_auto_coarsen swaps engine._staging to
    float32 for device-full runs, but the mesh staging sites used to
    re-resolve dtype="auto" via the config — which returns bfloat16 on
    TPU — silently staging bf16 under a float32 ordering contract. CPU
    can't hit the TPU branch of resolve_dtype, so simulate it: force
    resolve_dtype to "bfloat16" and assert staging follows the ENGINE's
    swapped state, not the config."""
    import ml_dtypes
    from dmlp_tpu.engine.single import no_auto_coarsen

    monkeypatch.setattr(EngineConfig, "resolve_dtype",
                        lambda self: "bfloat16" if self.dtype == "auto"
                        else self.dtype)
    text = generate_input_text(64, 6, 3, -2, 2, 1, 4, 2, seed=7)
    inp = parse_input_text(text)
    eng = ShardedEngine(EngineConfig(mode="sharded", dtype="auto"),
                        mesh=make_mesh((4, 2)))
    assert eng._staging == "bfloat16"
    assert eng._np_dtype() == ml_dtypes.bfloat16
    d_attrs, _, _, q_attrs = eng._shard_inputs(inp, 8)
    assert d_attrs.dtype == jnp.bfloat16 and q_attrs.dtype == jnp.bfloat16
    with no_auto_coarsen(eng):
        assert eng._staging == "float32"
        assert eng._np_dtype() == np.float32
        d_attrs, _, _, q_attrs = eng._shard_inputs(inp, 8)
        assert d_attrs.dtype == jnp.float32, \
            "device-full staging must follow the swapped engine state"
        assert q_attrs.dtype == jnp.float32
    # Swap restored after the context.
    assert eng._staging == "bfloat16"
