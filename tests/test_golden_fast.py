"""knn_golden_fast must equal the strict oracle, including under ties."""

import numpy as np

from dmlp_tpu.golden.fast import knn_golden_fast
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text

from tests.test_engine_single import assert_same_results


def test_fast_golden_matches_strict_continuous():
    inp = parse_input_text(generate_input_text(2000, 150, 12, -50, 50,
                                               1, 24, 8, seed=3))
    assert_same_results(knn_golden_fast(inp), knn_golden(inp))


def test_fast_golden_matches_strict_tie_heavy():
    rng = np.random.default_rng(5)
    data = rng.integers(0, 3, size=(500, 3)).astype(np.float64)
    queries = rng.integers(0, 3, size=(40, 3)).astype(np.float64)
    labels = rng.integers(0, 4, size=500).astype(np.int32)
    ks = rng.integers(1, 30, size=40).astype(np.int32)
    inp = KNNInput(Params(500, 40, 3), labels, data, ks, queries)
    assert_same_results(knn_golden_fast(inp), knn_golden(inp))


def test_fast_golden_tiny_margin_forces_fallback():
    # margin=0 means the candidate boundary sits on the k-th entry; the
    # safety check must route tie-heavy queries to the strict fallback and
    # still return exact results.
    rng = np.random.default_rng(6)
    data = rng.integers(0, 2, size=(300, 2)).astype(np.float64)
    queries = rng.integers(0, 2, size=(20, 2)).astype(np.float64)
    labels = rng.integers(0, 5, size=300).astype(np.int32)
    ks = np.full(20, 9, np.int32)
    inp = KNNInput(Params(300, 20, 2), labels, data, ks, queries)
    assert_same_results(knn_golden_fast(inp, margin=0), knn_golden(inp))


def test_fast_golden_k_exceeds_data():
    inp = KNNInput(Params(3, 2, 2),
                   np.array([0, 1, 2], np.int32),
                   np.array([[0.0, 0], [1, 1], [2, 2]]),
                   np.array([5, 2], np.int32),
                   np.array([[0.1, 0.1], [1.5, 1.5]]))
    assert_same_results(knn_golden_fast(inp), knn_golden(inp),
                        check_dists=False)
