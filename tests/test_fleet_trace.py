"""Request-scoped fleet tracing: rid plumbing under races, the causal
merge, and tail attribution.

The integration tests run a REAL in-process fleet (daemons + router
share this process's Tracer — complete_at spans from every layer land
in one event list) and race it: a replica crash mid-request, a drain
racing a query wave, ingest concurrent with queries. The tool tests
drive tools/merge_traces.py --fleet, tools/check_trace.py --fleet and
tools/tail_attrib.py on synthetic per-process traces with KNOWN clock
offsets and phase durations, so alignment and reconcile arithmetic are
asserted exactly, not just smoke-level.
"""

import json
import socket
import threading

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.fleet.router import FleetRouter
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.obs import trace as obs_trace
from dmlp_tpu.serve import client as sc
from dmlp_tpu.serve.daemon import ServeDaemon


def make_corpus(n=300, na=4, labels=4, seed=3, spread=50.0) -> KNNInput:
    rng = np.random.default_rng(seed)
    return KNNInput(Params(n, 0, na),
                    rng.integers(0, labels, n).astype(np.int32),
                    rng.uniform(0.0, spread, (n, na)),
                    np.zeros(0, np.int32), np.zeros((0, na)))


def _start_daemon(corpus, **kw):
    kw.setdefault("tick_s", 0.001)
    d = ServeDaemon(corpus, kw.pop("config", EngineConfig()), port=0,
                    **kw)
    d.start()
    return d


def _query(port, corpus, rid, nq=2, seed=61, k=8):
    rng = np.random.default_rng(seed)
    q = rng.uniform(0.0, 50.0, (nq, corpus.params.num_attrs))
    cli = sc.ServeClient(port)
    try:
        return cli.call({"op": "query", "id": rid, "rid": rid,
                         "queries": q.tolist(), "k": k})
    finally:
        cli.close()


@pytest.fixture
def tracer():
    t = obs_trace.install(obs_trace.Tracer())
    t.sync_instant("fleet.clock_sync")
    yield t
    obs_trace.uninstall()


def _spans(tracer, name, rid=None):
    out = []
    for e in tracer.to_dict()["traceEvents"]:
        if e.get("ph") != "X" or e.get("name") != name:
            continue
        if rid is not None and e.get("args", {}).get("rid") != rid:
            continue
        out.append(e)
    return out


class _CrashingReplica:
    """Healthy to stats probes, closes the connection on any query."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    doc = json.loads(conn.makefile("rb").readline())
                    if doc.get("op") == "stats":
                        conn.sendall(json.dumps(
                            {"ok": True, "stats": {"admission":
                             {"draining": False}}}).encode() + b"\n")
                    elif doc.get("op") == "drain":
                        conn.sendall(b'{"ok": true, "draining": true}\n')
                except (OSError, ValueError):
                    pass

    def close(self):
        self.sock.close()


# ---------------------------------------------------------------------------
# races
# ---------------------------------------------------------------------------


def test_rid_survives_crash_retry_with_two_hop_spans(tracer):
    """One rid, one crashed attempt, one successful retry: the causal
    tree shows BOTH replica attempts as child hop spans of one route
    span, and the response admits hops=2."""
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    crasher = _CrashingReplica()
    router = FleetRouter([("127.0.0.1", crasher.port),
                          ("127.0.0.1", d1.port)], port=0,
                         health_interval_s=600)
    router.start()
    try:
        # Route until one request actually hits the crasher first (the
        # picker balances by load, so the first try may land healthy).
        retried = None
        for i in range(6):
            r = _query(router.port, corpus, f"race-{i}")
            assert r["ok"], r
            assert r["rid"] == f"race-{i}"
            if r.get("hops"):
                retried = r
                break
        assert retried is not None, "no request was ever retried"
        rid = retried["rid"]
        assert retried["hops"] == 2
        hops = _spans(tracer, "fleet.hop", rid=rid)
        assert len(hops) == 2, hops
        assert sorted(h["args"]["attempt"] for h in hops) == [1, 2]
        outcomes = [h["args"]["outcome"] for h in hops]
        assert outcomes[0].startswith("error_"), outcomes
        assert outcomes[1] == "ok", outcomes
        (route,) = _spans(tracer, "fleet.route", rid=rid)
        assert route["args"]["hops"] == 2
        assert route["args"]["outcome"] == "ok"
        # The surviving replica's phase spans carry the same rid.
        assert _spans(tracer, "serve.phase.solve", rid=rid)
        assert _spans(tracer, "serve.phase.queue", rid=rid)
    finally:
        router.close()
        d1.close()
        crasher.close()


def test_drain_racing_query_wave_sheds_with_terminal_spans(tracer):
    """Requests shed by a draining router still produce their terminal
    fleet.route span — the merged tree explains every rid."""
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    router = FleetRouter([("127.0.0.1", d1.port)], port=0,
                         health_interval_s=600)
    router.start()
    try:
        out = {}

        def worker(rid):
            out[rid] = _query(router.port, corpus, rid)

        pre = [threading.Thread(target=worker, args=(f"w-{i}",))
               for i in range(3)]
        for t in pre:
            t.start()
        for t in pre:
            t.join(timeout=60)
        with router._lock:          # the drain hits mid-wave
            router._draining = True
        post = [threading.Thread(target=worker, args=(f"w-{i}",))
                for i in range(3, 6)]
        for t in post:
            t.start()
        for t in post:
            t.join(timeout=60)
        assert len(out) == 6
        for i in range(6):
            rid = f"w-{i}"
            routes = _spans(tracer, "fleet.route", rid=rid)
            assert len(routes) == 1, (rid, routes)
            if i < 3:
                assert out[rid]["ok"], out[rid]
                assert routes[0]["args"]["outcome"] == "ok"
            else:
                assert not out[rid]["ok"]
                assert "draining" in out[rid]["error"]
                assert routes[0]["args"]["outcome"] == \
                    "rejected_draining"
    finally:
        router.close()
        d1.close()


def test_concurrent_ingest_and_queries_never_share_a_rid(tracer):
    """Ingest fan-out is traced (fanout hop spans + replica ingest
    phases) but its rid never mixes with query rids — the cross-op
    uniqueness check_trace --fleet enforces."""
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    d2 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    router = FleetRouter([("127.0.0.1", d1.port),
                          ("127.0.0.1", d2.port)], port=0,
                         health_interval_s=600)
    router.start()
    try:
        rng = np.random.default_rng(7)
        rows = rng.uniform(0.0, 50.0, (5, corpus.params.num_attrs))
        results = {}

        def do_ingest():
            cli = sc.ServeClient(router.port)
            try:
                results["ing"] = cli.call(
                    {"op": "ingest", "id": "ing", "rid": "ing-0",
                     "labels": [0, 1, 2, 3, 0],
                     "rows": rows.tolist()})
            finally:
                cli.close()

        def do_query(i):
            results[f"q-{i}"] = _query(router.port, corpus, f"q-{i}")

        threads = [threading.Thread(target=do_ingest)] + \
            [threading.Thread(target=do_query, args=(i,))
             for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert results["ing"]["ok"], results["ing"]
        assert results["ing"]["rid"] == "ing-0"
        ing_hops = _spans(tracer, "fleet.hop", rid="ing-0")
        assert len(ing_hops) == 2            # fan-out to BOTH replicas
        assert all(h["args"].get("fanout") for h in ing_hops)
        assert _spans(tracer, "serve.phase.ingest", rid="ing-0")
        query_rids = set()
        for h in _spans(tracer, "fleet.hop"):
            if "attempt" in h["args"]:
                query_rids.add(h["args"]["rid"])
        assert query_rids == {f"q-{i}" for i in range(4)}
        assert "ing-0" not in query_rids
        for i in range(4):
            assert results[f"q-{i}"]["ok"]
    finally:
        router.close()
        d1.close()
        d2.close()


def test_untraced_requests_emit_no_spans_and_echo_no_rid():
    """Zero-cost default: no sink installed, no rid sent — the daemon
    answers byte-identically to the pre-rid protocol and the tracer
    hook stays cold."""
    assert not obs_trace.sinks_active()
    corpus = make_corpus()
    d1 = _start_daemon(corpus, warm_buckets=[(2, 8)])
    try:
        rng = np.random.default_rng(61)
        q = rng.uniform(0.0, 50.0, (2, corpus.params.num_attrs))
        cli = sc.ServeClient(d1.port)
        r = cli.call({"op": "query", "id": "0", "queries": q.tolist(),
                      "k": 8})
        cli.close()
        assert r["ok"]
        assert "rid" not in r
    finally:
        d1.close()


# ---------------------------------------------------------------------------
# merge / check / attribution tools on synthetic traces
# ---------------------------------------------------------------------------


def _doc(pid, pname, sync_ts, sync_unix_ms, events):
    evs = [{"name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": pname}},
           {"name": "fleet.clock_sync", "ph": "i", "ts": sync_ts,
            "s": "t", "pid": pid, "tid": 0,
            "args": {"unix_ms": sync_unix_ms}}]
    return {"traceEvents": evs + events, "displayTimeUnit": "ms",
            "clock": {"source": "monotonic"}}


def _x(name, ts, dur, pid, **args):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": pid,
            "tid": 0, "args": args}


def _write_fleet_dir(tmp_path, client_ms=20.0, phases=None):
    phases = phases or {"queue": 2.0, "coalesce": 1.0, "solve": 10.0,
                        "finalize": 1.0, "write": 0.5}
    rid = "r-0"
    client = _doc(4242, "client", 0.0, 999.9, [
        _x("client.request", 1000.0, client_ms * 1e3, 4242, rid=rid,
           lag_ms=0.5, ok=True, hops=1, level=4.0)])
    router = _doc(4343, "router", 500.0, 1000.0, [
        _x("fleet.route", 2000.0, 18000.0, 4343, op="query", rid=rid,
           outcome="ok", hops=1),
        _x("fleet.hop", 2100.0, 17000.0, 4343, attempt=1,
           replica="127.0.0.1:1", outcome="ok", rid=rid)])
    t = 99000.0
    pevs = []
    for ph in ("queue", "coalesce", "solve", "finalize", "write"):
        pevs.append(_x(f"serve.phase.{ph}", t, phases[ph] * 1e3, 4444,
                       rid=rid))
        t += phases[ph] * 1e3
    replica = _doc(4444, "serve:1", 99000.0, 1000.2, pevs)
    for fname, doc in (("trace-client.json", client),
                       ("trace-router.json", router),
                       ("trace-replica00.json", replica)):
        (tmp_path / fname).write_text(json.dumps(doc))
    return rid


def test_merge_fleet_aligns_clocks_and_reconciles(tmp_path):
    from tools.merge_traces import merge_fleet
    rid = _write_fleet_dir(tmp_path)
    merged = merge_fleet(str(tmp_path))
    off = merged["fleet"]["clock_offsets_us"]
    # off_p = ts_sync_ref - ts_sync_p + (unix_p - unix_ref) * 1000
    assert off["router"] == 0.0
    assert off["client"] == pytest.approx(500.0 - 0.0 - 100.0)
    assert off["replica00"] == pytest.approx(500.0 - 99000.0 + 200.0)
    assert all(e["ts"] >= 0 for e in merged["traceEvents"]
               if "ts" in e)
    # pids reassigned: client 0, router 1, replica 10
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {0, 1, 10}
    ent = merged["fleet"]["requests"][rid]
    assert ent["client"]["client_ms"] == pytest.approx(20.0)
    assert ent["phase_sum_ms"] == pytest.approx(14.5)
    # residual = 20.0 - 0.5 - 14.5
    assert ent["residual_ms"] == pytest.approx(5.0)
    assert ent["reconciled"] is True
    rec = merged["fleet"]["reconcile"]
    assert (rec["n_requests"], rec["n_reconciled"]) == (1, 1)


def test_merge_fleet_flags_out_of_tolerance_residual(tmp_path):
    from tools.merge_traces import merge_fleet
    # 400 ms client latency over a 14.5 ms phase sum: the residual
    # blows every default budget -> reconciled False, fraction 0.
    rid = _write_fleet_dir(tmp_path, client_ms=400.0)
    merged = merge_fleet(str(tmp_path))
    ent = merged["fleet"]["requests"][rid]
    assert ent["reconciled"] is False
    assert merged["fleet"]["reconcile"]["fraction"] == 0.0
    # ...and a widened absolute budget accepts it (CLI-overridable).
    merged = merge_fleet(str(tmp_path), tol_abs_ms=500.0)
    assert merged["fleet"]["requests"][rid]["reconciled"] is True


def test_merge_fleet_without_client_marks_unavailable(tmp_path):
    from tools.merge_traces import merge_fleet
    _write_fleet_dir(tmp_path)
    (tmp_path / "trace-client.json").unlink()
    merged = merge_fleet(str(tmp_path))
    rec = merged["fleet"]["reconcile"]
    assert "reconcile_unavailable" in rec
    assert "fraction" not in rec


def test_check_fleet_passes_good_and_rejects_tampered(tmp_path, capsys):
    from tools.check_trace import check_fleet_trace
    from tools.merge_traces import merge_fleet
    rid = _write_fleet_dir(tmp_path)
    merged = merge_fleet(str(tmp_path))
    good = tmp_path / "merged.json"
    good.write_text(json.dumps(merged))
    check_fleet_trace(str(good))          # must not exit
    capsys.readouterr()
    # orphan phase span: a rid with no fleet.route root
    bad = dict(merged)
    bad["traceEvents"] = merged["traceEvents"] + [
        _x("serve.phase.solve", 1.0, 1.0, 10, rid="ghost")]
    p = tmp_path / "orphan.json"
    p.write_text(json.dumps(bad))
    with pytest.raises(SystemExit):
        check_fleet_trace(str(p))
    # fabricated retry hop on a single-hop request
    bad["traceEvents"] = merged["traceEvents"] + [
        _x("fleet.hop", 1.0, 1.0, 1, rid=rid, attempt=2,
           replica="fake", outcome="ok")]
    p.write_text(json.dumps(bad))
    with pytest.raises(SystemExit):
        check_fleet_trace(str(p))
    # duplicated rid: two client.request spans
    bad["traceEvents"] = merged["traceEvents"] + [
        _x("client.request", 1.0, 1.0, 0, rid=rid, lag_ms=0.0,
           ok=True, hops=1)]
    p.write_text(json.dumps(bad))
    with pytest.raises(SystemExit):
        check_fleet_trace(str(p))


def test_tail_attrib_names_the_dominant_phase(tmp_path):
    from tools.merge_traces import merge_fleet
    from tools.tail_attrib import attribute
    _write_fleet_dir(tmp_path)
    merged = merge_fleet(str(tmp_path))
    levels = attribute(merged)
    assert sorted(levels) == ["x4"]
    att = levels["x4"]
    assert att["n"] == 1
    p99 = att["quantiles"]["p99"]
    assert p99["phases"]["solve"] == pytest.approx(10.0)
    assert att["dominant_p99"] == "solve"
    # client_ms excludes the pacing lag; residual is the un-phased rest
    assert p99["client_ms"] == pytest.approx(19.5)
    assert p99["residual_ms"] == pytest.approx(5.0)


def test_tailattrib_records_land_as_gated_phase_series(tmp_path):
    from dmlp_tpu.obs.ledger import ingest_file
    from dmlp_tpu.obs.run import RunRecord
    rec = RunRecord(kind="tailattrib", tool="tools.tail_attrib",
                    config={"level": "x8", "dominant_p99": "queue"},
                    metrics={"queue_p99_ms": 12.5, "solve_p99_ms": 8.0})
    path = tmp_path / "TAILATTRIB.jsonl"
    rec.append_jsonl(str(path))
    entry = ingest_file(str(path))
    assert entry["status"] == "parsed"
    series = {p["series"] for p in entry["points"]}
    assert "fleet/x8/phase/queue_p99_ms" in series
    assert "fleet/x8/phase/solve_p99_ms" in series
