"""The fast "topk" selection path: parity incl. adversarial tie overflow.

The ``lax.top_k`` path keeps distance ties by position, not by the
reference's larger-id preference (dmlp_tpu.ops.topk). These
tests force ``select="topk"`` (every other test resolves "auto" -> "sort"
at test sizes) and cover the case code review flagged: a duplicate tie
group larger than k + margin straddling the candidate boundary, where the
candidate set itself is wrong and only the boundary_overflow repair can
restore golden parity.
"""

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.finalize import boundary_overflow
from dmlp_tpu.engine.sharded import RingEngine, ShardedEngine
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text
from dmlp_tpu.parallel.mesh import make_mesh

from tests.test_engine_single import assert_same_results


def duplicate_overflow_input():
    """32 copies of the queried point (k=5, margin 16 -> width 24 < 32):
    the fast path's candidate set cannot hold the full tie group."""
    rng = np.random.default_rng(1)
    far = rng.uniform(50, 60, size=(32, 4))
    near = np.tile(np.array([[1.0, 2.0, 3.0, 4.0]]), (32, 1))
    data = np.concatenate([near, far])
    labels = np.concatenate([np.arange(32) % 7,
                             np.zeros(32)]).astype(np.int32)
    queries = np.array([[1.0, 2.0, 3.0, 4.0]])
    ks = np.array([5], np.int32)
    return KNNInput(Params(64, 1, 4), labels, data, ks, queries)


@pytest.mark.parametrize("exact", [True, False])
def test_single_topk_tie_overflow_repair(exact):
    inp = duplicate_overflow_input()
    eng = SingleChipEngine(EngineConfig(select="topk", exact=exact,
                                        data_block=16, query_block=8))
    assert_same_results(eng.run(inp), knn_golden(inp), check_dists=exact)


def test_overflow_detector_flags_tie_at_boundary():
    d = np.array([[0.0, 1.0, 2.0, 2.0]], np.float32)
    assert boundary_overflow(d, np.array([3])).tolist() == [True]
    assert boundary_overflow(d, np.array([2])).tolist() == [False]
    # +inf tail = candidate list not even full: nothing truncated.
    dinf = np.array([[0.0, 1.0, np.inf, np.inf]], np.float32)
    assert boundary_overflow(dinf, np.array([4])).tolist() == [False]


def test_fast_mode_topk_keeps_detector_slack():
    # Regression (code review): with exact=False the margin used to be 0,
    # making ks == kcap and the overflow detector flag *every* query —
    # the "fast" path then ran the host oracle on the whole problem. The
    # topk path must always carry extra candidate slots.
    text = generate_input_text(300, 30, 6, -5, 5, 4, 12, 4, seed=2)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(select="topk", exact=False,
                                        data_block=64, query_block=8))
    dists, _, _ = eng.candidates(inp)
    assert dists.shape[1] > int(inp.ks.max())
    assert not boundary_overflow(dists, inp.ks).any()


def test_single_topk_matches_golden_continuous():
    text = generate_input_text(700, 60, 6, -5, 5, 1, 20, 4, seed=31)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(select="topk", data_block=64,
                                        query_block=16))
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_single_seg_gather_path_matches_golden():
    # The gather/cond path only traces when nseg > k + 16: kmax=4 with
    # margin 0 (slack bumps it to 8) gives selection width k=16, S=32;
    # data_block=8192 -> nseg=64 > 32. (Smaller tiles hit the static
    # s == nseg full branch and never compile the gather — review finding.)
    text = generate_input_text(9000, 60, 6, -5, 5, 1, 4, 4, seed=41)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(select="seg", data_block=8192,
                                        query_block=16, margin=0))
    assert eng._prep(inp)[3] + 16 < 8192 // 128  # gather path is live
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_single_seg_hazard_fallback_tie_heavy():
    # Integer grid in 2D: massive duplicate distances tie the segment
    # minima at the threshold, so the in-jit hazard cond must route to the
    # full top_k branch and preserve exactness. nseg=72 > S so the cond is
    # actually compiled (not the static full branch).
    rng = np.random.default_rng(8)
    n = 9216
    data = rng.integers(0, 4, size=(n, 2)).astype(np.float64)
    queries = rng.integers(0, 4, size=(24, 2)).astype(np.float64)
    labels = rng.integers(0, 5, size=n).astype(np.int32)
    ks = rng.integers(1, 8, size=24).astype(np.int32)
    inp = KNNInput(Params(n, 24, 2), labels, data, ks, queries)
    eng = SingleChipEngine(EngineConfig(select="seg", data_block=9216,
                                        query_block=8, margin=0))
    assert eng._prep(inp)[3] + 16 < 9216 // 128
    assert_same_results(eng.run(inp), knn_golden(inp))


def test_seg_small_block_falls_back_to_topk():
    # data_block not a multiple of 128 -> streaming_topk silently uses the
    # topk step; results still golden.
    text = generate_input_text(600, 30, 4, 0, 9, 1, 8, 3, seed=17)
    inp = parse_input_text(text)
    eng = SingleChipEngine(EngineConfig(select="seg", data_block=72,
                                        query_block=8))
    assert_same_results(eng.run(inp), knn_golden(inp))


@pytest.mark.parametrize("cls,mode", [(ShardedEngine, "sharded"),
                                      (RingEngine, "ring")])
def test_mesh_seg_matches_golden(cls, mode):
    text = generate_input_text(4096, 48, 5, 0, 10, 1, 16, 6, seed=23)
    inp = parse_input_text(text)
    eng = cls(EngineConfig(mode=mode, select="seg", data_block=256,
                           query_block=8), mesh=make_mesh())
    assert_same_results(eng.run(inp), knn_golden(inp))


@pytest.mark.parametrize("cls,mode", [(ShardedEngine, "sharded"),
                                      (RingEngine, "ring")])
def test_mesh_topk_tie_overflow_repair(cls, mode):
    inp = duplicate_overflow_input()
    eng = cls(EngineConfig(mode=mode, select="topk", data_block=8,
                           query_block=8), mesh=make_mesh())
    assert_same_results(eng.run(inp), knn_golden(inp))


@pytest.mark.parametrize("cls,mode", [(ShardedEngine, "sharded"),
                                      (RingEngine, "ring")])
def test_mesh_topk_matches_golden_continuous(cls, mode):
    text = generate_input_text(400, 40, 5, 0, 10, 1, 16, 6, seed=13)
    inp = parse_input_text(text)
    eng = cls(EngineConfig(mode=mode, select="topk", data_block=16,
                           query_block=8), mesh=make_mesh())
    assert_same_results(eng.run(inp), knn_golden(inp))
