"""dmlp_tpu.check — the static analysis suite.

Three layers: (1) fixture snippets per rule family, positive AND
negative, proving each seeded violation class is caught and each
legitimate idiom is not; (2) the REAL package, which must be clean of
non-baselined findings (the committed baseline is empty — keep it so);
(3) the baseline round-trip (new finding fails -> baselined passes ->
fixed reports stale) and the ``--json`` CLI contract.
"""

import json
import os
import subprocess
import sys
import textwrap

from dmlp_tpu.check.analyzer import (analyze_package, analyze_paths,
                                     package_root)
from dmlp_tpu.check.baseline import (diff_baseline, load_baseline,
                                     save_baseline)


def write(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return str(p)


def rules_of(findings):
    return sorted(f.rule for f in findings)


def run_check(tmp_path, families):
    return analyze_paths([str(tmp_path)], families, root=str(tmp_path))


# ---------------------------------------------------------------------------
# R1 — collective-axis contract
# ---------------------------------------------------------------------------

MESH_SRC = """
DATA_AXIS = "data"
QUERY_AXIS = "query"
"""


class TestR1Collectives:
    def test_r101_undeclared_axis_caught(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            def f(x):
                return jax.lax.psum(x, "bogus")
        """)
        fs = run_check(tmp_path, ["R1"])
        assert "R101" in rules_of(fs)
        assert any("bogus" in f.message for f in fs)

    def test_r101_declared_axis_clean_incl_constant(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            from dmlp_tpu.parallel.mesh import DATA_AXIS
            def f(x):
                return jax.lax.psum(x, DATA_AXIS) + \\
                    jax.lax.axis_index("query")
        """)
        assert run_check(tmp_path, ["R1"]) == []

    def test_r102_axis_not_in_shard_map_specs(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from dmlp_tpu.utils.compat import shard_map
            from jax.sharding import PartitionSpec as P

            def build(mesh):
                def local(a):
                    return jax.lax.psum(a, "query")  # check: no-traffic
                return shard_map(local, mesh=mesh,
                                 in_specs=(P("data"),),
                                 out_specs=P("data"))
        """)
        fs = run_check(tmp_path, ["R1"])
        assert "R102" in rules_of(fs)

    def test_r102_spec_axis_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from dmlp_tpu.utils.compat import shard_map
            from jax.sharding import PartitionSpec as P

            def build(mesh):
                def local(a):
                    return jax.lax.psum(a, "data")  # check: no-traffic
                return shard_map(local, mesh=mesh,
                                 in_specs=(P("data"),),
                                 out_specs=P("data"))
        """)
        assert run_check(tmp_path, ["R1"]) == []

    def test_r103_unannotated_traffic_collective(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/train/x.py", """
            import jax
            def f(x):
                return jax.lax.psum(x, "data")
        """)
        assert "R103" in rules_of(run_check(tmp_path, ["R1"]))

    def test_r103_annotated_with_real_model_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/obs/comms.py", """
            def psum_traffic(nbytes, axis_size):
                return nbytes
        """)
        write(tmp_path, "dmlp_tpu/train/x.py", """
            import jax
            def f(x):
                # check: comms-model=psum_traffic
                return jax.lax.psum(x, "data")
        """)
        assert run_check(tmp_path, ["R1"]) == []

    def test_r104_annotation_names_missing_model(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/obs/comms.py", "def real_model():\n    pass\n")
        write(tmp_path, "dmlp_tpu/train/x.py", """
            import jax
            def f(x):
                # check: comms-model=renamed_away_traffic
                return jax.lax.psum(x, "data")
        """)
        assert "R104" in rules_of(run_check(tmp_path, ["R1"]))

    def test_axis_helper_call_site_checked(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/parallel/helpers.py", """
            import jax
            def merge(local, k, axis_name):
                # check: comms-model=m
                return jax.lax.all_gather(local, axis_name)
        """)
        write(tmp_path, "dmlp_tpu/obs/comms.py", "def m():\n    pass\n")
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.parallel.helpers import merge
            def f(local, k):
                return merge(local, k, "not_an_axis")
        """)
        fs = run_check(tmp_path, ["R1"])
        assert "R101" in rules_of(fs)
        assert any(f.path.endswith("engine/x.py") for f in fs)


# ---------------------------------------------------------------------------
# R105/R106 — kernel-dispatch cost coverage (R1 family)
# ---------------------------------------------------------------------------


class TestDispatchCost:
    def test_r105_dispatch_without_probe(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.obs import counters as obs_counters
            from dmlp_tpu.ops.pallas_fused import fused_topk

            def drive(q, d):
                obs_counters.record_dispatch(fused_topk, (q, d), site="s")
                return fused_topk(q, d, n_real=4, kc=8)
        """)
        fs = run_check(tmp_path, ["R1"])
        assert "R105" in rules_of(fs)
        assert any("MeasuredIters" in f.message for f in fs)

    def test_r105_resolver_bound_kernel_var_covered(self, tmp_path):
        """``kern, impl = resolve_topk_kernel(...)`` binds a kernel
        variable — dispatching it without a probe is the same hole."""
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.obs import counters as obs_counters
            from dmlp_tpu.ops import pallas_fused

            def drive(q, d):
                kern, impl = pallas_fused.resolve_topk_kernel(8, 8, 8, 8)
                obs_counters.record_dispatch(kern, (q, d), site="s")
                return kern(q, d, n_real=4, kc=8)
        """)
        assert "R105" in rules_of(run_check(tmp_path, ["R1"]))

    def test_r105_probe_in_function_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.engine.single import MeasuredIters
            from dmlp_tpu.obs import counters as obs_counters
            from dmlp_tpu.ops.pallas_fused import fused_topk

            def drive(eng, q, d):
                mi = MeasuredIters(eng, "s", (1, 2, 3, 4),
                                   kernel="fused")
                obs_counters.record_dispatch(fused_topk, (q, d), site="s")
                od, oi, it = fused_topk(q, d, n_real=4, kc=8)
                mi.add(it)
                mi.done()
                return od
        """)
        assert run_check(tmp_path, ["R1"]) == []

    def test_r105_queue_iters_protocol_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.obs import counters as obs_counters
            from dmlp_tpu.ops import pallas_fused

            def drive(self, q, d):
                kern, impl = pallas_fused.resolve_topk_kernel(8, 8, 8, 8)
                obs_counters.record_dispatch(kern, (q, d), site="s")
                od, oi, it = kern(q, d, n_real=4, kc=8)
                self._queue_iters("s", "extract", it, 8, 8, 8, 8,
                                  impl=impl)
                return od
        """)
        assert run_check(tmp_path, ["R1"]) == []

    def test_r106_unmodeled_ops_kernel(self, tmp_path):
        """A kernel imported from dmlp_tpu.ops with no analytic_cost
        registry entry (parsed from the REAL kernel_cost.py) fails —
        the fused-megakernel drift class."""
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.engine.single import MeasuredIters
            from dmlp_tpu.obs import counters as obs_counters
            from dmlp_tpu.ops.pallas_next import hyper_kernel

            def drive(eng, q, d):
                mi = MeasuredIters(eng, "s", (1, 2, 3, 4))
                obs_counters.record_dispatch(hyper_kernel, (q, d),
                                             site="s")
                od, oi, it = hyper_kernel(q, d, n_real=4, kc=8)
                mi.add(it)
                mi.done()
                return od
        """)
        fs = run_check(tmp_path, ["R1"])
        assert rules_of(fs) == ["R106"]
        assert any("hyper_kernel" in f.message for f in fs)

    def test_r106_registered_kernels_clean(self, tmp_path):
        """extract_topk and fused_topk ARE in the parsed model table —
        this pins the registry parse itself (an empty parse would make
        R106 fire on every legitimate dispatch or none)."""
        from dmlp_tpu.check.analyzer import load_modules
        from dmlp_tpu.check.dispatchcost import _modeled_kernels
        mods, _ = load_modules([package_root()])
        modeled = _modeled_kernels(mods)
        assert {"extract_topk", "fused_topk",
                "fused_dist_segmin"} <= modeled

    def test_r105_allow_directive(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.obs import counters as obs_counters
            from dmlp_tpu.ops.pallas_fused import fused_topk

            def drive(q, d):
                # check: allow-collective
                obs_counters.record_dispatch(fused_topk, (q, d), site="s")
                return fused_topk(q, d, n_real=4, kc=8)
        """)
        assert run_check(tmp_path, ["R1"]) == []

    def test_r105_outside_engine_ignored(self, tmp_path):
        """tools/bench measure what they please — engine/ only."""
        write(tmp_path, "dmlp_tpu/bench/x.py", """
            from dmlp_tpu.obs import counters as obs_counters
            from dmlp_tpu.ops.pallas_fused import fused_topk

            def drive(q, d):
                obs_counters.record_dispatch(fused_topk, (q, d), site="s")
                return fused_topk(q, d, n_real=4, kc=8)
        """)
        assert run_check(tmp_path, ["R1"]) == []


# ---------------------------------------------------------------------------
# R2 — recompilation hazards
# ---------------------------------------------------------------------------


class TestR2Recompile:
    def test_r203_fused_selection_inside_jit(self, tmp_path):
        """ISSUE 8 small fix: the fused/two-pass selection
        (resolve_topk_kernel, and the kill-switch read behind it) is
        the PR 3 in-jit-resolution bug class — R203 must provably
        cover it so the choice is always part of the jit cache key."""
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from dmlp_tpu.ops.pallas_fused import resolve_topk_kernel

            @jax.jit
            def solve(q, d):
                kern, impl = resolve_topk_kernel(8, 8, 8, 8)
                return kern(q, d, n_real=4, kc=8)
        """)
        fs = run_check(tmp_path, ["R2"])
        assert "R203" in rules_of(fs)
        assert any("resolve_topk_kernel" in f.message for f in fs)

    def test_r203_fused_kill_switch_read_inside_jit(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from dmlp_tpu.ops.pallas_fused import fused_enabled

            @jax.jit
            def solve(q, d):
                if fused_enabled():
                    return q
                return d
        """)
        assert "R203" in rules_of(run_check(tmp_path, ["R2"]))

    def test_r203_fused_selection_outside_jit_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import functools
            import jax
            from dmlp_tpu.ops.pallas_fused import resolve_topk_kernel

            def solve(q, d):
                kern, impl = resolve_topk_kernel(8, 8, 8, 8)
                run = jax.jit(functools.partial(kern, n_real=4, kc=8))
                return run(q, d)
        """)
        assert "R203" not in rules_of(run_check(tmp_path, ["R2"]))
    def test_r201_mutable_default_on_jit(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            @jax.jit
            def f(x, opts=[]):
                return x
        """)
        assert "R201" in rules_of(run_check(tmp_path, ["R2"]))

    def test_r202_fstring_in_jit_body(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            @jax.jit
            def f(x):
                name = f"variant_{x.shape}"
                return x, name
        """)
        assert "R202" in rules_of(run_check(tmp_path, ["R2"]))

    def test_r202_fstring_in_raise_is_fine(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            @jax.jit
            def f(x):
                if x.shape[0] % 8:
                    raise ValueError(f"bad shape {x.shape}")
                return x
        """)
        assert run_check(tmp_path, ["R2"]) == []

    def test_r203_variant_resolution_inside_jit(self, tmp_path):
        # The PR 3 review bug, reduced: lookup_variant consulted inside
        # the traced body -> stale-trace reuse after a cache update.
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            from dmlp_tpu.tune import lookup_variant
            @jax.jit
            def f(x):
                v = lookup_variant(8, x.shape[0])
                return x * v["ne"]
        """)
        assert "R203" in rules_of(run_check(tmp_path, ["R2"]))

    def test_r203_resolution_outside_jit_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            from dmlp_tpu.tune import lookup_variant
            @jax.jit
            def _impl(x, ne):
                return x * ne
            def f(x):
                v = lookup_variant(8, x.shape[0])
                return _impl(x, v["ne"])
        """)
        assert run_check(tmp_path, ["R2"]) == []

    def test_r204_obviously_static_kwonly_missing(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import functools
            import jax
            @functools.partial(jax.jit, static_argnames=("k",))
            def f(x, *, k, select):
                return x[:k] if select == "sort" else x
        """)
        fs = run_check(tmp_path, ["R2"])
        assert "R204" in rules_of(fs)
        assert any("select" in f.message for f in fs)

    def test_r204_traced_kwonly_names_not_flagged(self, tmp_path):
        # n_real/id_base/floor style params are legitimately traced.
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import functools
            import jax
            @functools.partial(jax.jit, static_argnames=("kc",))
            def f(x, *, n_real, id_base, kc, floor):
                return x[:kc] + n_real + id_base
        """)
        assert run_check(tmp_path, ["R2"]) == []

    def test_r205_closure_over_module_mutable(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            _CACHE = {}
            @jax.jit
            def f(x):
                return x * len(_CACHE)
        """)
        assert "R205" in rules_of(run_check(tmp_path, ["R2"]))

    def test_shard_mapped_body_is_traced_too(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.utils.compat import shard_map
            def build(mesh, specs):
                def local(a):
                    tag = f"cell_{a.shape}"
                    return a, tag
                return shard_map(local, mesh=mesh, in_specs=specs,
                                 out_specs=specs)
        """)
        assert "R202" in rules_of(run_check(tmp_path, ["R2"]))


# ---------------------------------------------------------------------------
# R3 — host-sync hazards
# ---------------------------------------------------------------------------


class TestR3HostSync:
    def test_r301_item(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            def f(arr):
                return arr.item()
        """)
        assert "R301" in rules_of(run_check(tmp_path, ["R3"]))

    def test_r302_device_get_needs_annotation(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            def f(arr):
                return jax.device_get(arr)
        """)
        assert "R302" in rules_of(run_check(tmp_path, ["R3"]))

    def test_allowlist_comment_silences(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            def f(arr):
                return jax.device_get(arr)  # check: allow-host-sync
        """)
        assert run_check(tmp_path, ["R3"]) == []

    def test_trailing_allowlist_does_not_leak_to_next_line(self, tmp_path):
        # A trailing directive covers ITS statement only; the
        # un-annotated implicit transfer on the next line must still
        # flag (review finding: `lineno - 1` lookups silently widened
        # every allowlist by one line).
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            import numpy as np
            import jax.numpy as jnp
            def f(x):
                fetched = jax.device_get(x)  # check: allow-host-sync
                return np.asarray(jnp.sum(x))
        """)
        assert "R304" in rules_of(run_check(tmp_path, ["R3"]))

    def test_r303_float_on_device_expr(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax.numpy as jnp
            def f(a, b):
                s = jnp.dot(a, b)
                return float(s)
        """)
        assert "R303" in rules_of(run_check(tmp_path, ["R3"]))

    def test_r304_np_asarray_on_device_expr(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import numpy as np
            import jax.numpy as jnp
            def f(a):
                out = jnp.sort(a)
                return np.asarray(out)
        """)
        assert "R304" in rules_of(run_check(tmp_path, ["R3"]))

    def test_device_get_launders_taint(self, tmp_path):
        # The sanctioned pattern: explicit fence, then host math freely.
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            import numpy as np
            import jax.numpy as jnp
            def f(a):
                out = jnp.sort(a)
                # check: allow-host-sync
                out = jax.device_get(out)
                return float(np.asarray(out)[0])
        """)
        assert run_check(tmp_path, ["R3"]) == []

    def test_host_numpy_untouched(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import numpy as np
            def f(attrs):
                a = np.zeros((8, 4), np.float32)
                a[:4] = attrs
                return float(np.einsum("na,na->n", a, a).max())
        """)
        assert run_check(tmp_path, ["R3"]) == []

    def test_r305_branch_on_traced_value(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            import jax.numpy as jnp
            @jax.jit
            def f(x):
                if jnp.sum(x) > 0:
                    return x
                return -x
        """)
        assert "R305" in rules_of(run_check(tmp_path, ["R3"]))

    def test_is_none_branch_in_jit_is_fine(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            import jax.numpy as jnp
            @jax.jit
            def f(x, carry):
                if carry is None:
                    carry = jnp.zeros_like(x)
                return x + carry
        """)
        assert run_check(tmp_path, ["R3"]) == []

    def test_out_of_scope_dirs_ignored(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            def f(arr):
                return arr.item()
        """)
        assert run_check(tmp_path, ["R3"]) == []


# ---------------------------------------------------------------------------
# R4 — compat-bypass
# ---------------------------------------------------------------------------


class TestR4Compat:
    def test_r401_shard_map_import(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from jax.experimental.shard_map import shard_map
        """)
        assert "R401" in rules_of(run_check(tmp_path, ["R4"]))

    def test_r402_axis_size_attr(self, tmp_path):
        write(tmp_path, "dmlp_tpu/train/x.py", """
            import jax
            def f(ax):
                return jax.lax.axis_size(ax)
        """)
        assert "R402" in rules_of(run_check(tmp_path, ["R4"]))

    def test_r403_compiler_params_attr(self, tmp_path):
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            from jax.experimental.pallas import tpu as pltpu
            def f():
                return pltpu.CompilerParams()
        """)
        assert "R403" in rules_of(run_check(tmp_path, ["R4"]))

    def test_r404_memory_kind_literal(self, tmp_path):
        write(tmp_path, "dmlp_tpu/train/x.py", """
            def f(sharding):
                return sharding.with_memory_kind("pinned_host")
        """)
        assert "R404" in rules_of(run_check(tmp_path, ["R4"]))

    def test_compat_module_exempt(self, tmp_path):
        write(tmp_path, "dmlp_tpu/utils/compat.py", """
            import jax
            def axis_size(ax):
                if hasattr(jax.lax, "axis_size"):
                    return jax.lax.axis_size(ax)
                return jax.lax.psum(1, ax)
            def host_memory_kind():
                return "pinned_host"
        """)
        assert run_check(tmp_path, ["R4"]) == []

    def test_docstring_mention_not_flagged(self, tmp_path):
        write(tmp_path, "dmlp_tpu/train/x.py", '''
            def f():
                """Docs may say "pinned_host" freely."""
                return None
        ''')
        assert run_check(tmp_path, ["R4"]) == []


# ---------------------------------------------------------------------------
# R5 — resilience-path silent swallowing
# ---------------------------------------------------------------------------


class TestR5Resilient:
    def test_r501_broad_swallow_in_resilience_module(self, tmp_path):
        write(tmp_path, "dmlp_tpu/resilience/x.py", """
            def f(op):
                try:
                    return op()
                except Exception:
                    return None
        """)
        assert "R501" in rules_of(run_check(tmp_path, ["R5"]))

    def test_r501_importer_of_resilience_in_scope(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from dmlp_tpu.resilience import retry as rs_retry
            def f(op):
                try:
                    return rs_retry.call_with_retry(op, "s")
                except Exception:
                    return None
        """)
        assert "R501" in rules_of(run_check(tmp_path, ["R5"]))

    def test_r501_reraise_is_compliant(self, tmp_path):
        write(tmp_path, "dmlp_tpu/resilience/x.py", """
            def f(op):
                try:
                    return op()
                except Exception as e:
                    raise RuntimeError("wrapped") from e
        """)
        assert run_check(tmp_path, ["R5"]) == []

    def test_r501_annotation_silences(self, tmp_path):
        write(tmp_path, "dmlp_tpu/resilience/x.py", """
            def f(op):
                try:
                    return op()
                except Exception:  # check: no-retry
                    return None
        """)
        assert run_check(tmp_path, ["R5"]) == []

    def test_r501_narrow_catch_is_fine(self, tmp_path):
        write(tmp_path, "dmlp_tpu/resilience/x.py", """
            def f(op):
                try:
                    return op()
                except ValueError:
                    return None
        """)
        assert run_check(tmp_path, ["R5"]) == []

    def test_r501_nested_def_raise_does_not_count(self, tmp_path):
        # Defining a raiser inside the handler is not raising: the
        # swallow still needs a re-raise or the annotation.
        write(tmp_path, "dmlp_tpu/resilience/x.py", """
            def f(op):
                try:
                    return op()
                except Exception:
                    def _report():
                        raise RuntimeError("later")
                    return None
        """)
        assert "R501" in rules_of(run_check(tmp_path, ["R5"]))

    def test_module_without_resilience_import_out_of_scope(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            def f(op):
                try:
                    return op()
                except Exception:
                    return None
        """)
        assert run_check(tmp_path, ["R5"]) == []

# ---------------------------------------------------------------------------
# R6 — telemetry metric-name contract (obs.telemetry registry)
# ---------------------------------------------------------------------------


class TestR6MetricNames:
    def test_r601_fstring_name_caught(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            def f(site):
                REGISTRY.counter(f"retries.{site}").inc()
        """)
        assert "R601" in rules_of(run_check(tmp_path, ["R6"]))

    def test_r601_variable_name_caught(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            from dmlp_tpu.obs import telemetry
            def f(name):
                telemetry.registry().gauge(name).set(1)
        """)
        assert "R601" in rules_of(run_check(tmp_path, ["R6"]))

    def test_r601_camelcase_literal_caught(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            REGISTRY.histogram("SolveLatencyMs")
        """)
        assert "R601" in rules_of(run_check(tmp_path, ["R6"]))

    def test_r601_literal_dotted_snake_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            def f(site):
                REGISTRY.counter("engine.retries").inc(label=site)
                REGISTRY.gauge("mem.device.bytes_in_use").set(1)
                REGISTRY.histogram("span.latency_ms").observe(2.5)
        """)
        assert run_check(tmp_path, ["R6"]) == []

    def test_r601_annotation_silences_deliberate_seam(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            def f(safe):
                h = REGISTRY.histogram(safe + ".ms")  # check: allow-metric-name
                h.observe(1.0)
        """)
        assert run_check(tmp_path, ["R6"]) == []

    def test_r602_conflicting_kinds_cross_module(self, tmp_path):
        write(tmp_path, "dmlp_tpu/obs/a.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            REGISTRY.counter("engine.solves")
        """)
        write(tmp_path, "dmlp_tpu/obs/b.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            REGISTRY.gauge("engine.solves")
        """)
        fs = run_check(tmp_path, ["R6"])
        assert "R602" in rules_of(fs)

    def test_r602_same_kind_many_sites_clean(self, tmp_path):
        # get-or-create is the contract: one name, one kind, any
        # number of use sites.
        write(tmp_path, "dmlp_tpu/obs/a.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            REGISTRY.counter("engine.solves")
        """)
        write(tmp_path, "dmlp_tpu/obs/b.py", """
            from dmlp_tpu.obs.telemetry import REGISTRY
            REGISTRY.counter("engine.solves").inc()
        """)
        assert run_check(tmp_path, ["R6"]) == []

    def test_non_registry_receiver_out_of_scope(self, tmp_path):
        # A collections.Counter-style .counter attr on a non-registry
        # object must not trip the rule.
        write(tmp_path, "dmlp_tpu/obs/x.py", """
            def f(store, name):
                store.counter(name)
        """)
        assert run_check(tmp_path, ["R6"]) == []


# ---------------------------------------------------------------------------
# R7 — concurrency discipline
# ---------------------------------------------------------------------------


class TestR7Concurrency:
    def test_r701_inversion_across_functions(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            LOCK_A = threading.Lock()
            LOCK_B = threading.Lock()
            def f():
                with LOCK_A:
                    with LOCK_B:
                        pass
            def g():
                with LOCK_B:
                    with LOCK_A:
                        pass
        """)
        fs = run_check(tmp_path, ["R7"])
        assert "R701" in rules_of(fs)
        assert any("inverts" in f.message for f in fs)

    def test_r701_consistent_order_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            LOCK_A = threading.Lock()
            LOCK_B = threading.Lock()
            def f():
                with LOCK_A:
                    with LOCK_B:
                        pass
            def g():
                with LOCK_A:
                    with LOCK_B:
                        pass
        """)
        assert run_check(tmp_path, ["R7"]) == []

    def test_r701_cross_module_inversion_via_call(self, tmp_path):
        # a holds A and calls b's taker (A->B); b holds B and calls
        # a's taker (B->A): the cycle spans modules and call chains.
        write(tmp_path, "dmlp_tpu/serve/a.py", """
            import threading
            from dmlp_tpu.serve.b import take_b
            LOCK_A = threading.Lock()
            def take_a():
                with LOCK_A:
                    pass
            def f():
                with LOCK_A:
                    take_b()
        """)
        write(tmp_path, "dmlp_tpu/serve/b.py", """
            import threading
            from dmlp_tpu.serve.a import take_a
            LOCK_B = threading.Lock()
            def take_b():
                with LOCK_B:
                    pass
            def g():
                with LOCK_B:
                    take_a()
        """)
        fs = run_check(tmp_path, ["R7"])
        assert rules_of(fs).count("R701") >= 2  # both edges flagged

    def test_r701_nested_nonreentrant_self_deadlock(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def f(self):
                    with self._lock:
                        with self._lock:
                            pass
        """)
        fs = run_check(tmp_path, ["R7"])
        assert "R701" in rules_of(fs)
        assert any("self-deadlock" in f.message for f in fs)

    def test_r702_unguarded_read_of_guarded_field(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                def add(self):
                    with self._lock:
                        self.n += 1
                def peek(self):
                    return self.n
        """)
        fs = run_check(tmp_path, ["R7"])
        assert "R702" in rules_of(fs)
        assert any("self.n" in f.message for f in fs)

    def test_r702_guarded_access_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                def add(self):
                    with self._lock:
                        self.n += 1
                def peek(self):
                    with self._lock:
                        return self.n
        """)
        assert run_check(tmp_path, ["R7"]) == []

    def test_r702_mutable_escape_by_reference(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []
                def add(self, x):
                    with self._lock:
                        self._items = self._items + [x]
                def items(self):
                    with self._lock:
                        return self._items
        """)
        fs = run_check(tmp_path, ["R7"])
        assert "R702" in rules_of(fs)
        assert any("escape" in f.key for f in fs)

    def test_r702_copy_return_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []
                def add(self, x):
                    with self._lock:
                        self._items = self._items + [x]
                def items(self):
                    with self._lock:
                        return list(self._items)
        """)
        assert run_check(tmp_path, ["R7"]) == []

    def test_r702_allow_directive_with_invariant(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                def add(self):
                    with self._lock:
                        self.n += 1
                def peek(self):
                    # check: allow-concurrency=R702 — racy int read is
                    # benign: single GIL load, monitoring only
                    return self.n
        """)
        assert run_check(tmp_path, ["R7"]) == []

    def test_r703_sleep_under_lock(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            import time
            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                def run(self):
                    with self._lock:
                        time.sleep(0.1)
        """)
        fs = run_check(tmp_path, ["R7"])
        assert "R703" in rules_of(fs)

    def test_r703_call_mediated_sleep_under_lock(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            import time
            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                def _nap(self):
                    time.sleep(0.01)
                def run(self):
                    with self._lock:
                        self._nap()
        """)
        fs = run_check(tmp_path, ["R7"])
        assert "R703" in rules_of(fs)
        assert any("_nap" in f.message for f in fs)

    def test_r703_sleep_outside_lock_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            import time
            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                def run(self):
                    with self._lock:
                        n = 1
                    time.sleep(0.1)
                    return n
        """)
        assert run_check(tmp_path, ["R7"]) == []

    def test_r703_condition_wait_on_held_lock_clean(self, tmp_path):
        # cond.wait RELEASES the held lock — the legal blocking wait.
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            class Q:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.items = []
                def get(self):
                    with self._cond:
                        while not self.items:
                            self._cond.wait(timeout=0.1)
                        return self.items.pop()
        """)
        fs = run_check(tmp_path, ["R7"])
        assert "R703" not in rules_of(fs)

    def test_r704_thread_without_daemon_or_join(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            def go(f):
                t = threading.Thread(target=f)
                t.start()
        """)
        assert "R704" in rules_of(run_check(tmp_path, ["R7"]))

    def test_r704_daemon_thread_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            def go(f):
                threading.Thread(target=f, daemon=True).start()
        """)
        assert run_check(tmp_path, ["R7"]) == []

    def test_r704_joined_thread_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/serve/x.py", """
            import threading
            def go(f):
                t = threading.Thread(target=f)
                t.start()
                t.join()
        """)
        assert run_check(tmp_path, ["R7"]) == []


# ---------------------------------------------------------------------------
# R9 — compiler-sharded (GSPMD) surface contract
# ---------------------------------------------------------------------------


class TestR9AutoShard:
    def test_r901_undeclared_pspec_axis_caught(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from jax.sharding import NamedSharding, PartitionSpec as P
            def shardings(mesh):
                return NamedSharding(mesh, P("dataa", None))
        """)
        fs = run_check(tmp_path, ["R9"])
        assert "R901" in rules_of(fs)
        assert any("dataa" in f.message for f in fs)

    def test_r901_declared_axes_and_none_entries_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from jax.sharding import NamedSharding, PartitionSpec as P
            from dmlp_tpu.parallel.mesh import DATA_AXIS, QUERY_AXIS
            def shardings(mesh):
                return (NamedSharding(mesh, P(DATA_AXIS, None, None)),
                        NamedSharding(mesh, P(QUERY_AXIS, None)),
                        NamedSharding(mesh, P()))
        """)
        assert run_check(tmp_path, ["R9"]) == []

    def test_r901_allow_directive_respected(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            from jax.sharding import PartitionSpec as P
            def spec():
                # check: allow-auto-shard=R901 — doc example axis
                return P("stage")
        """)
        assert run_check(tmp_path, ["R9"]) == []

    def test_r902_unpinned_jit_in_auto_engine_caught(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/engine/auto.py", """
            import jax
            def build(fn):
                return jax.jit(fn)
        """)
        fs = run_check(tmp_path, ["R9"])
        assert "R902" in rules_of(fs)
        assert any("in_shardings" in f.message for f in fs)

    def test_r902_pinned_jit_clean_and_other_files_exempt(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/engine/auto.py", """
            import jax
            def build(fn, ins, outs):
                return jax.jit(fn, in_shardings=ins, out_shardings=outs)
        """)
        write(tmp_path, "dmlp_tpu/engine/other.py", """
            import jax
            def build(fn):
                return jax.jit(fn)
        """)
        assert run_check(tmp_path, ["R9"]) == []


# ---------------------------------------------------------------------------
# --stale-allows + the fingerprint cache
# ---------------------------------------------------------------------------


class TestStaleAllows:
    def test_dead_directive_reported_live_one_kept(self, tmp_path):
        from dmlp_tpu.check.analyzer import (analyze_paths_tracking,
                                             stale_allow_directives)
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            def live(arr):
                return jax.device_get(arr)  # check: allow-host-sync
            def dead(arr):
                return arr  # check: allow-host-sync
        """)
        _fs, mods = analyze_paths_tracking(
            [str(tmp_path)], ["R0", "R1", "R2", "R3", "R4", "R5", "R6",
                              "R7"], root=str(tmp_path))
        stale = stale_allow_directives(mods)
        assert [(ln, d) for _p, ln, d in stale] == \
            [(6, "allow-host-sync")]

    def test_prose_mentions_not_reported(self, tmp_path):
        from dmlp_tpu.check.analyzer import (analyze_paths_tracking,
                                             stale_allow_directives)
        write(tmp_path, "dmlp_tpu/obs/x.py", '''
            def f():
                """Docs may say annotate `# check: no-retry` freely."""
                return 1
        ''')
        _fs, mods = analyze_paths_tracking(
            [str(tmp_path)], ["R5"], root=str(tmp_path))
        assert stale_allow_directives(mods) == []

    def test_cli_stale_allows_json(self, tmp_path):
        write(tmp_path, "dmlp_tpu/engine/x.py", """
            def dead(arr):
                return arr  # check: allow-host-sync
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "dmlp_tpu.check", "--stale-allows",
             "--json", str(tmp_path / "dmlp_tpu")],
            capture_output=True, text=True, env=env)
        assert r.returncode == 1
        verdict = json.loads(r.stdout)
        assert verdict["ok"] is False
        assert verdict["stale_allows"][0]["directive"] == \
            "allow-host-sync"


VIOLATION_R1 = """
import jax
def f(x):
    return jax.lax.psum(x, "bogus")
"""


class TestFingerprintCache:
    def _cache(self, tmp_path):
        from dmlp_tpu.check.cache import CheckCache
        return CheckCache(directory=str(tmp_path / "cache"),
                          enabled=True)

    def test_second_run_hits_and_findings_identical(self, tmp_path):
        from dmlp_tpu.check.analyzer import analyze_paths
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/ops/x.py", VIOLATION_R1)
        c1 = self._cache(tmp_path)
        cold = analyze_paths([str(tmp_path)], ["R1"],
                             root=str(tmp_path), cache=c1)
        assert c1.misses == 2 and c1.hits == 0
        c2 = self._cache(tmp_path)
        warm = analyze_paths([str(tmp_path)], ["R1"],
                             root=str(tmp_path), cache=c2)
        assert c2.hits == 2 and c2.misses == 0
        assert [f.fingerprint() for f in warm] == \
            [f.fingerprint() for f in cold]
        assert "R101" in rules_of(warm)

    def test_edit_invalidates_only_the_changed_file(self, tmp_path):
        from dmlp_tpu.check.analyzer import analyze_paths
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        src = write(tmp_path, "dmlp_tpu/ops/x.py", VIOLATION_R1)
        analyze_paths([str(tmp_path)], ["R1"], root=str(tmp_path),
                      cache=self._cache(tmp_path))
        # facts-neutral edit (a comment): only x.py re-analyzes
        with open(src) as f:
            body = f.read()
        open(src, "w").write("# shifted\n" + body)
        c = self._cache(tmp_path)
        fs = analyze_paths([str(tmp_path)], ["R1"], root=str(tmp_path),
                           cache=c)
        assert c.hits == 1 and c.misses == 1
        assert "R101" in rules_of(fs)
        # the fix lands -> cached verdict must NOT resurrect the finding
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            def f(x):
                return jax.lax.psum(x, "data")  # check: no-traffic
        """)
        fs2 = analyze_paths([str(tmp_path)], ["R1"], root=str(tmp_path),
                            cache=self._cache(tmp_path))
        assert fs2 == []

    def test_facts_change_invalidates_everyone(self, tmp_path):
        from dmlp_tpu.check.analyzer import analyze_paths
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/ops/x.py", VIOLATION_R1)
        analyze_paths([str(tmp_path)], ["R1"], root=str(tmp_path),
                      cache=self._cache(tmp_path))
        # declaring the axis changes mesh.py's FACTS: the other file's
        # cached (now wrong) verdict must be invalidated too
        write(tmp_path, "dmlp_tpu/parallel/mesh.py",
              MESH_SRC + 'BOGUS_AXIS = "bogus"\n')
        c = self._cache(tmp_path)
        fs = analyze_paths([str(tmp_path)], ["R1"], root=str(tmp_path),
                           cache=c)
        assert fs == []              # the axis is declared now
        assert c.hits == 0           # every findings entry missed

    def test_disabled_cache_is_noop(self, tmp_path):
        from dmlp_tpu.check.analyzer import analyze_paths
        from dmlp_tpu.check.cache import CheckCache
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/ops/x.py", VIOLATION_R1)
        c = CheckCache(directory=str(tmp_path / "cache"), enabled=False)
        fs = analyze_paths([str(tmp_path)], ["R1"], root=str(tmp_path),
                           cache=c)
        assert "R101" in rules_of(fs)
        assert not (tmp_path / "cache").exists()


# ---------------------------------------------------------------------------
# R0 — hygiene (the ruff-subset fallback behind make lint)
# ---------------------------------------------------------------------------


class TestR0Hygiene:
    def test_unused_import(self, tmp_path):
        write(tmp_path, "dmlp_tpu/x.py", """
            import os
            import sys
            print(sys.argv)
        """)
        fs = run_check(tmp_path, ["R0"])
        assert rules_of(fs) == ["R001"]
        assert "os" in fs[0].message

    def test_noqa_and_init_reexports_respected(self, tmp_path):
        write(tmp_path, "dmlp_tpu/x.py", """
            import os  # noqa: F401
        """)
        write(tmp_path, "dmlp_tpu/__init__.py", """
            from dmlp_tpu.x import thing
        """)
        assert run_check(tmp_path, ["R0"]) == []

    def test_bare_except(self, tmp_path):
        write(tmp_path, "dmlp_tpu/x.py", """
            def f():
                try:
                    return 1
                except:
                    return 0
        """)
        assert "R002" in rules_of(run_check(tmp_path, ["R0"]))

    def test_mutable_default(self, tmp_path):
        write(tmp_path, "dmlp_tpu/x.py", """
            def f(xs=[]):
                return xs
        """)
        assert "R003" in rules_of(run_check(tmp_path, ["R0"]))

    def test_fstring_without_placeholder(self, tmp_path):
        write(tmp_path, "dmlp_tpu/x.py", """
            def f():
                return f"static text"
        """)
        assert "R004" in rules_of(run_check(tmp_path, ["R0"]))

    def test_format_spec_fstrings_not_flagged(self, tmp_path):
        # py3.10 nests the ":.6f" spec as its own JoinedStr — must not
        # false-positive (the bug the first run over the tree surfaced).
        write(tmp_path, "dmlp_tpu/x.py", """
            def f(v):
                return f"{v:.6f}"
        """)
        assert run_check(tmp_path, ["R0"]) == []


# ---------------------------------------------------------------------------
# the real package + baseline + CLI
# ---------------------------------------------------------------------------


def test_real_package_clean_of_default_family_findings():
    """R1-R4 over the installed package: zero findings. Anything new
    must be fixed or explicitly baselined in check_baseline.json."""
    assert analyze_package() == []


def test_real_package_clean_of_hygiene_findings():
    assert analyze_package(["R0"]) == []


def test_committed_baseline_is_empty_and_loadable():
    path = os.path.join(os.path.dirname(package_root()),
                        "check_baseline.json")
    assert os.path.exists(path), "check_baseline.json must be committed"
    assert sum(load_baseline(path).values()) == 0


VIOLATION = """
import jax
def f(x):
    return jax.lax.psum(x, "bogus")
"""


class TestBaselineRoundTrip:
    def test_new_finding_then_baseline_then_stale(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        src = write(tmp_path, "dmlp_tpu/ops/x.py", VIOLATION)
        findings = run_check(tmp_path, ["R1"])
        assert findings  # the seeded violation is caught

        # un-baselined -> new (fails make check)
        new, matched, stale = diff_baseline(findings, {})
        assert new and not matched and not stale

        # baselined -> passes
        bl_path = str(tmp_path / "check_baseline.json")
        save_baseline(bl_path, findings)
        new, matched, stale = diff_baseline(findings,
                                            load_baseline(bl_path))
        assert not new and len(matched) == len(findings) and not stale

        # baseline survives unrelated line shifts (fingerprint has no
        # line numbers)
        with open(src) as f:
            shifted = "# a new comment line\n" + f.read()
        open(src, "w").write(shifted)
        findings2 = run_check(tmp_path, ["R1"])
        new, matched, _ = diff_baseline(findings2, load_baseline(bl_path))
        assert not new and matched

        # fixed -> stale baseline entry reported, exit stays clean
        write(tmp_path, "dmlp_tpu/ops/x.py", """
            import jax
            DATA = "data"
            def f(x):
                return jax.lax.psum(x, "data")  # check: no-traffic
        """)
        findings3 = run_check(tmp_path, ["R1"])
        new, _, stale = diff_baseline(findings3, load_baseline(bl_path))
        assert not new and stale


class TestCLI:
    def _run(self, args, cwd=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "dmlp_tpu.check", *args],
            capture_output=True, text=True, env=env, cwd=cwd)

    def test_json_verdict_pure_stdout_and_exit_codes(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/ops/x.py", VIOLATION)
        r = self._run(["--json", "--families", "R1", "--no-baseline",
                       str(tmp_path / "dmlp_tpu")])
        assert r.returncode == 1
        verdict = json.loads(r.stdout)  # stdout is pure JSON
        assert verdict["ok"] is False
        assert any(f["rule"] == "R101" for f in verdict["new"])
        assert "finding" in r.stderr  # narration on stderr

    def test_write_baseline_then_clean(self, tmp_path):
        write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        write(tmp_path, "dmlp_tpu/ops/x.py", VIOLATION)
        bl = str(tmp_path / "bl.json")
        target = str(tmp_path / "dmlp_tpu")
        assert self._run(["--families", "R1", "--write-baseline",
                          "--baseline", bl, target]).returncode == 0
        r = self._run(["--families", "R1", "--baseline", bl, target])
        assert r.returncode == 0

    def test_list_rules(self, tmp_path):
        r = self._run(["--list-rules"])
        assert r.returncode == 0
        for rule in ("R101", "R203", "R302", "R404", "R001"):
            assert rule in r.stdout
