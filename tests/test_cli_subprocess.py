"""Real-pipe CLI tests + adversarial duplicate fuzzing (VERDICT r1 item 7).

The in-process CLI tests (test_cli.py) never exercise the actual
stdin-file-descriptor path or the >= 1 MB native-parser dispatch
(io/grammar._NATIVE_THRESHOLD_BYTES) end-to-end; these do, by spawning
``python -m dmlp_tpu`` exactly the way the grader would run
``./engine < input``.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.ring import RingEngine
from dmlp_tpu.engine.sharded import ShardedEngine
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text
from dmlp_tpu.io.report import format_results


def _run_cli_subprocess(text: str, *args: str):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "dmlp_tpu", *args],
        input=text.encode(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=env, cwd=repo, timeout=240)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc.stdout.decode(), proc.stderr.decode()


def test_subprocess_pipe_large_input_native_parser_path():
    """>= 1 MB stdin over a real pipe: parse_input must take the native C++
    tokenizer branch (grammar.py _NATIVE_THRESHOLD_BYTES) and the output
    must match the golden oracle byte for byte."""
    # ~2000 rows x 64 attrs x ~9 bytes/field ~= 1.2 MB
    text = generate_input_text(2000, 40, 64, 0.0, 100.0, 1, 16, 8, seed=5)
    assert len(text.encode()) >= (1 << 20)
    want = format_results(knn_golden(parse_input_text(text)))
    out, err = _run_cli_subprocess(text)
    assert out == want
    assert "Time taken:" in err


def test_subprocess_pipe_debug_mode():
    text = generate_input_text(120, 6, 4, 0.0, 9.0, 1, 5, 3, seed=8)
    want = format_results(knn_golden(parse_input_text(text)), debug=True)
    out, _ = _run_cli_subprocess(text, "--debug")
    assert out == want


def _duplicate_heavy_input(rng, n, q, a, num_labels, k_hi):
    """Adversarial instance: attributes drawn from a tiny value set, so
    distance ties (including whole tie groups straddling the candidate
    boundary) are everywhere."""
    vals = np.array([0.0, 1.0, 2.0])
    data = rng.choice(vals, size=(n, a))
    queries = rng.choice(vals, size=(q, a))
    labels = rng.integers(0, num_labels, n).astype(np.int32)
    ks = rng.integers(1, k_hi + 1, q).astype(np.int32)
    return KNNInput(Params(n, q, a), labels, np.asarray(data, np.float64),
                    ks, np.asarray(queries, np.float64))


@pytest.mark.parametrize("select", ["sort", "topk", "seg"])
def test_fuzz_duplicate_heavy_all_engines_vs_golden(select):
    """Seeded fuzz loop: 3 engines x this select on duplicate-heavy data
    must equal golden checksums exactly (the boundary repair is what makes
    the fast selects exact — asserted separately below)."""
    rng = np.random.default_rng(1234)
    for trial in range(4):
        inp = _duplicate_heavy_input(rng, n=128 + 32 * trial, q=12, a=3,
                                     num_labels=4, k_hi=10)
        want = [r.checksum() for r in knn_golden(inp)]
        engines = [
            SingleChipEngine(EngineConfig(select=select, data_block=32,
                                          query_block=8)),
            ShardedEngine(EngineConfig(mode="sharded", select=select,
                                       data_block=16, query_block=8)),
            RingEngine(EngineConfig(mode="ring", select=select,
                                    data_block=16, query_block=8)),
        ]
        for eng in engines:
            got = [r.checksum() for r in eng.run(inp)]
            assert got == want, (select, trial, type(eng).__name__)


def test_boundary_overflow_repair_actually_fires():
    """Statistical check on the repair machinery itself: on duplicate-heavy
    data the device tie-overflow flags must trigger for some queries (if
    they never fire, the 'repair' path is dead code and parity on the topk
    path is luck)."""
    from dmlp_tpu.engine import finalize as fin

    rng = np.random.default_rng(77)
    inp = _duplicate_heavy_input(rng, n=256, q=16, a=2, num_labels=3,
                                 k_hi=12)
    calls = []
    orig = fin.repair_boundary_overflow

    eng = SingleChipEngine(EngineConfig(select="topk", data_block=32,
                                        query_block=8))
    import dmlp_tpu.engine.single as single_mod
    try:
        single_mod.repair_boundary_overflow = \
            lambda *a, **kw: (calls.append(len(a[1])), orig(*a, **kw))[1]
        got = [r.checksum() for r in eng.run(inp)]
    finally:
        single_mod.repair_boundary_overflow = orig
    want = [r.checksum() for r in knn_golden(inp)]
    assert got == want
    assert calls and calls[0] > 0, "tie-overflow repair never fired"
