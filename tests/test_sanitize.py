"""Runtime sanitizer (dmlp_tpu.check.sanitize): the sanitized tier-1
subset.

Proves three things on this backend: (1) the guard has TEETH — an
implicit host sync inside ``sanitized()`` raises; (2) the engines'
solve paths are transfer-clean end to end — a sanitized solve completes
and is byte-identical to the unsanitized one (single run / device-full
/ sharded / ring, plus the real CLI with ``--sanitize``); (3) the env
var / flag plumbing.
"""

import contextlib
import io

import jax
import jax.numpy as jnp
import pytest

from dmlp_tpu.check.sanitize import (maybe_sanitized, sanitize_enabled,
                                     sanitized)
from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import parse_input_text
from dmlp_tpu.io.report import format_results


@pytest.fixture
def small_input():
    text = generate_input_text(300, 40, 8, -10, 10, 1, 12, 5, seed=21)
    return parse_input_text(text)


def _checksums(results):
    return [r.checksum() for r in results]


def test_guard_has_teeth_implicit_sync_raises():
    x = jax.jit(lambda a: a * 2)(jnp.arange(8.0))
    with sanitized():
        with pytest.raises(Exception, match="[Dd]isallow"):
            float(x[0])  # implicit device->host scalar conversion
        # the explicit fence stays allowed — that's the R3 discipline
        assert float(jax.device_get(x)[0]) == 0.0


def test_guard_blocks_implicit_staging():
    import numpy as np
    f = jax.jit(lambda a: a + 1)
    with sanitized():
        with pytest.raises(Exception, match="[Dd]isallow"):
            f(np.ones(8, np.float32))  # implicit host->device at jit edge
        f(jax.device_put(np.ones(8, np.float32)))  # explicit: fine


def test_single_engine_sanitized_byte_identical(small_input):
    eng = SingleChipEngine(EngineConfig(data_block=64, query_block=16))
    plain = _checksums(eng.run(small_input))
    with sanitized():
        assert _checksums(eng.run(small_input)) == plain


def test_single_engine_device_full_sanitized(small_input):
    eng = SingleChipEngine(EngineConfig(data_block=64, query_block=16))
    plain = _checksums(eng.run_device_full(small_input))
    with sanitized():
        assert _checksums(eng.run_device_full(small_input)) == plain


@pytest.mark.parametrize("mode", ["sharded", "ring"])
def test_mesh_engines_sanitized(small_input, mode):
    from dmlp_tpu.engine.ring import RingEngine
    from dmlp_tpu.engine.sharded import ShardedEngine
    cls = ShardedEngine if mode == "sharded" else RingEngine
    eng = cls(EngineConfig(mode=mode, data_block=64, query_block=16))
    plain = _checksums(eng.run(small_input))
    with sanitized():
        assert _checksums(eng.run(small_input)) == plain


def test_cli_sanitize_flag_byte_identical(small_input):
    from dmlp_tpu.cli import main
    text = generate_input_text(200, 20, 6, -5, 5, 1, 9, 4, seed=7)

    def run(argv):
        out, err = io.StringIO(), io.StringIO()
        rc = main(argv, stdin=io.StringIO(text), stdout=out, stderr=err)
        assert rc == 0
        assert "Time taken:" in err.getvalue()
        return out.getvalue()

    assert run(["--sanitize"]) == run([])


def test_golden_results_unchanged_under_sanitize(small_input):
    # The float64 oracle is pure numpy — trivially clean, and it pins
    # that the sanitized jax solve still matches golden exactly.
    from dmlp_tpu.golden.reference import knn_golden
    eng = SingleChipEngine(EngineConfig(data_block=64, query_block=16))
    want = format_results(knn_golden(small_input))
    with sanitized():
        assert format_results(eng.run(small_input)) == want


def test_sanitize_enabled_env_parsing():
    assert not sanitize_enabled({})
    for v in ("1", "true", "ON", "yes"):
        assert sanitize_enabled({"DMLP_TPU_SANITIZE": v})
    for v in ("0", "false", "", "off"):
        assert not sanitize_enabled({"DMLP_TPU_SANITIZE": v})


def test_maybe_sanitized_plumbing():
    cm = maybe_sanitized(environ={})
    assert isinstance(cm, contextlib.nullcontext)
    assert not isinstance(maybe_sanitized(force=True),
                          contextlib.nullcontext)
    assert not isinstance(
        maybe_sanitized(environ={"DMLP_TPU_SANITIZE": "1"}),
        contextlib.nullcontext)


def test_sanitized_train_step_runs():
    # dp_tp step on the 8 virtual devices, two steps under the train
    # guard (h2d+d2h disallowed, debug_nans on): completes and the loss
    # is finite.
    from dmlp_tpu.train.loop import train
    _, last = train(steps=2, batch=64, dims=(8, 16, 4),
                    mesh_shape=(2, 2), log_every=1, sanitize=True)
    assert last["step"] == 2
    assert last["loss"] == last["loss"]  # not NaN
