"""Live-telemetry tests: registry semantics, histogram quantile error
bounds vs numpy.percentile, thread safety under concurrent writers,
sampler start/stop idempotence, OpenMetrics export validity, the
analytic peak-HBM model (hand-computed per engine), watermark
reconciliation markers, the flight recorder's dump triggers (including
an injected fatal fault carrying the last N spans), registry-backed
resilience counters, and the CLI/ledger integration."""

import json
import math
import os
import threading

import numpy as np
import pytest

from dmlp_tpu.obs import memwatch, telemetry
from dmlp_tpu.obs.telemetry import (HIST_QUANTILE_REL_ERROR,
                                    FlightRecorder, Histogram, Registry,
                                    Sampler, validate_openmetrics)


@pytest.fixture(autouse=True)
def _clean_process_state():
    """Every test sees a quiet process registry and no leftover
    session (telemetry state is process-global by design)."""
    s = telemetry.session()
    if s is not None:
        s.close()
    telemetry.REGISTRY.reset()
    yield
    s = telemetry.session()
    if s is not None:
        s.close()
    telemetry.REGISTRY.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_get_or_create_returns_same_object(self):
        r = Registry()
        assert r.counter("a.b") is r.counter("a.b")

    def test_kind_conflict_raises(self):
        r = Registry()
        r.counter("a.b")
        with pytest.raises(TypeError, match="already registered"):
            r.gauge("a.b")

    def test_bad_name_rejected(self):
        r = Registry()
        for bad in ("CamelCase", "has-dash", "1leading", "dotted..twice",
                    "trailing."):
            with pytest.raises(ValueError, match="snake_case"):
                r.counter(bad)

    def test_counter_monotonic_and_labeled(self):
        c = Registry().counter("x.y")
        c.inc()
        c.inc(2, label="site_a")
        c.inc(3, label="site_b")
        assert c.total() == 6
        assert c.by_label() == {"site_a": 2, "site_b": 3}
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_last_write_wins(self):
        g = Registry().gauge("g.v")
        g.set(1)
        g.set(7.5)
        assert g.value() == 7.5

    def test_reset_prefix_scoped(self):
        r = Registry()
        r.counter("resilience.retries").inc()
        r.counter("engine.solves").inc()
        r.reset(prefix="resilience")
        assert r.get("resilience.retries") is None
        assert r.get("engine.solves").total() == 1

    def test_snapshot_shape(self):
        r = Registry()
        r.counter("c.n").inc(3)
        r.gauge("g.n").set(2)
        h = r.histogram("h.n", unit="ms")
        h.observe(5.0)
        snap = r.snapshot()
        assert snap["c.n"] == {"kind": "counter", "total": 3}
        assert snap["g.n"] == {"kind": "gauge", "value": 2.0}
        assert snap["h.n"]["count"] == 1 and snap["h.n"]["kind"] == \
            "histogram"


# ---------------------------------------------------------------------------
# histogram quantile error bound
# ---------------------------------------------------------------------------


class TestHistogramQuantiles:
    @pytest.mark.parametrize("dist", ["lognormal", "uniform", "bimodal"])
    def test_quantiles_within_documented_bound(self, dist):
        rng = np.random.RandomState(42)
        if dist == "lognormal":
            vals = rng.lognormal(3.0, 1.0, 20000)
        elif dist == "uniform":
            vals = rng.uniform(0.5, 500.0, 20000)
        else:
            # 60/40 split so no tested quantile lands in the empty
            # inter-mode gap (where ANY estimator is ambiguous: there
            # are no samples to be close to).
            vals = np.concatenate([rng.normal(10, 1, 12000),
                                   rng.normal(300, 30, 8000)])
            vals = np.clip(vals, 0.01, None)
        h = Histogram("t.ms")
        for v in vals:
            h.observe(float(v))
        # The estimate is the geometric bucket midpoint: its error vs
        # the true histogram quantile is <= HIST_QUANTILE_REL_ERROR;
        # vs numpy.percentile an extra half-bucket of rank discreteness
        # can stack, hence the 2x envelope (documented bound x2 is
        # still < 12% relative).
        tol = 2 * HIST_QUANTILE_REL_ERROR
        for q in (0.50, 0.95, 0.99):
            ref = float(np.percentile(vals, q * 100))
            est = h.quantile(q)
            assert abs(est - ref) / ref <= tol, (dist, q, est, ref)

    def test_min_max_exact_and_clamping(self):
        h = Histogram("t.ms")
        for v in (0.0001, 5.0, 123456.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["min"] == 0.0001 and snap["max"] == 123456.0
        assert h.quantile(0.0) >= snap["min"]
        assert h.quantile(1.0) <= snap["max"]

    def test_empty_and_nan_samples(self):
        h = Histogram("t.ms")
        assert math.isnan(h.quantile(0.5))
        h.observe(float("nan"))    # must not poison
        assert h.count == 0
        h.observe(2.0)
        assert h.count == 1

    def test_bucket_index_edges_consistent(self):
        # Exactly-on-boundary values must land in the bucket whose
        # upper bound they equal (le semantics), never one off.
        h = Histogram("t.ms")
        from dmlp_tpu.obs.telemetry import _BOUNDS
        for b in _BOUNDS[:50]:
            i = h.bucket_index(b)
            assert b <= _BOUNDS[i]
            assert i == 0 or b > _BOUNDS[i - 1]


# ---------------------------------------------------------------------------
# thread safety
# ---------------------------------------------------------------------------


class TestThreadSafety:
    def test_concurrent_writers_lose_nothing(self):
        r = Registry()
        n_threads, n_iters = 8, 2000

        def work(tid):
            c = r.counter("t.hits")
            h = r.histogram("t.ms")
            g = r.gauge("t.last")
            for i in range(n_iters):
                c.inc(label=f"w{tid}")
                h.observe(1.0 + (i % 100))
                g.set(i)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert r.counter("t.hits").total() == n_threads * n_iters
        assert r.histogram("t.ms").count == n_threads * n_iters
        # concurrent registration of ONE name returns one object
        assert len(r.counter("t.hits").by_label()) == n_threads

    def test_concurrent_get_or_create_one_instance(self):
        r = Registry()
        out = []

        def reg():
            out.append(r.counter("race.c"))

        threads = [threading.Thread(target=reg) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(o is out[0] for o in out)


# ---------------------------------------------------------------------------
# sampler
# ---------------------------------------------------------------------------


class TestSampler:
    def test_start_stop_idempotent(self):
        s = Sampler(interval_s=0.01)
        s.start()
        first = s._thread
        s.start()                       # second start: no new thread
        assert s._thread is first
        s.stop()
        s.stop()                        # second stop: no-op
        assert not s.running

    def test_sample_now_sets_mem_gauges(self):
        import jax
        keep = jax.numpy.zeros(8)     # a LIVE array while we sample
        keep.block_until_ready()
        s = Sampler(interval_s=60)
        s.sample_now()
        del keep
        # CPU backend: memory_stats is None -> honest marker gauge;
        # live arrays still measured.
        assert telemetry.REGISTRY.gauge(
            "mem.stats_unavailable").value() == 1
        assert telemetry.REGISTRY.gauge(
            "mem.live_array_bytes").value() > 0
        assert s.measured_peak()["basis"] == "live_arrays"

    def test_heartbeat_age_gauge(self, tmp_path, monkeypatch):
        hb = tmp_path / "beat"
        hb.write_text("x")
        monkeypatch.setenv("DMLP_TPU_HEARTBEAT", str(hb))
        s = Sampler(interval_s=60)
        s.sample_now()
        age = telemetry.REGISTRY.gauge("heartbeat.age_s").value()
        assert age is not None and 0 <= age < 60


# ---------------------------------------------------------------------------
# OpenMetrics export
# ---------------------------------------------------------------------------


class TestOpenMetrics:
    def test_export_validates_and_round_trips(self):
        r = Registry()
        r.counter("engine.solves").inc(3)
        r.counter("engine.retries").inc(2, label="stage_put")
        r.gauge("mem.stats_unavailable").set(1)
        h = r.histogram("span.latency_ms", unit="ms")
        for v in (1.0, 5.0, 250.0):
            h.observe(v)
        text = r.to_openmetrics()
        assert validate_openmetrics(text) == []
        assert text.endswith("# EOF\n")
        assert "engine_solves_total 3" in text
        assert 'engine_retries_total{key="stage_put"} 2' in text
        assert "span_latency_ms_count 3" in text
        assert 'span_latency_ms_bucket{le="+Inf"} 3' in text

    def test_validator_catches_breakage(self):
        assert validate_openmetrics("garbage\n") != []
        assert any("EOF" in p for p in validate_openmetrics("x 1\n"))
        # undeclared sample name
        bad = "# TYPE a counter\nb_total 1\n# EOF"
        assert any("no preceding" in p for p in validate_openmetrics(bad))
        nonnum = "# TYPE a gauge\na wat\n# EOF"
        assert any("non-numeric" in p for p in validate_openmetrics(nonnum))

    def test_validator_accepts_tiny_values_the_emitter_writes(self):
        # repr(5e-05) is '5e-05': negative-exponent scientific notation
        # must validate — a sub-100ns span once failed the whole smoke.
        r = Registry()
        r.gauge("tiny.v").set(5e-05)
        h = r.histogram("tiny.ms")
        h.observe(5e-05)
        assert validate_openmetrics(r.to_openmetrics()) == []

    def test_exemplars_render_and_validate(self):
        # The last exemplar-carrying observation per bucket is exposed
        # as a '# EXEMPLAR' comment line after its bucket sample —
        # tolerated by the validator, linking a tail bucket back to
        # one rid in the merged fleet trace.
        r = Registry()
        h = r.histogram("fleet.request_latency_ms", unit="ms")
        h.observe(2.0, exemplar="x2-0")
        h.observe(2.1, exemplar="x2-5")   # same bucket: last wins
        h.observe(400.0, exemplar="x8-3")
        h.observe(7.0)                    # no exemplar: no comment
        text = r.to_openmetrics()
        assert validate_openmetrics(text) == []
        lines = text.splitlines()
        ex = [ln for ln in lines if ln.startswith("# EXEMPLAR ")]
        assert len(ex) == 2, text
        assert any("x2-5" in ln for ln in ex)
        assert all("x2-0" not in ln for ln in ex)
        assert any("x8-3" in ln for ln in ex)
        # each exemplar comment follows its bucket sample line
        for ln in ex:
            bucket = ln.split(" ", 2)[2].rsplit(" ", 2)[0]
            i = lines.index(ln)
            assert lines[i - 1].startswith(bucket + " "), (bucket, ln)

    def test_exemplar_free_exposition_is_byte_stable(self):
        # observe() without the kwarg must render exactly as before —
        # the exemplar seam is opt-in per observation.
        r1, r2 = Registry(), Registry()
        for reg in (r1, r2):
            h = reg.histogram("span.latency_ms", unit="ms")
            for v in (1.0, 5.0, 250.0):
                h.observe(v)
        assert r1.to_openmetrics() == r2.to_openmetrics()
        assert "# EXEMPLAR" not in r1.to_openmetrics()

    def test_http_endpoint_serves_metrics(self):
        import urllib.request
        telemetry.REGISTRY.counter("http.hits").inc(5)
        s = telemetry.start(port=0, handle_signals=False)
        try:
            url = f"http://127.0.0.1:{s.http_port}/metrics"
            body = urllib.request.urlopen(url, timeout=10).read().decode()
            assert validate_openmetrics(body) == []
            assert "http_hits_total 5" in body
        finally:
            s.close()


# ---------------------------------------------------------------------------
# analytic peak-HBM model — hand-computed per engine
# ---------------------------------------------------------------------------


class TestMemwatchModel:
    def test_single_chunked_topk_hand_computed(self):
        # n=20000 a=32 q=1000 kmax=16, default config on CPU: select
        # resolves "topk" (padded 20000 > AUTO_SELECT_THRESHOLD, no
        # pallas). plan_chunks(20000, 8, None): one 20000-row chunk.
        # kcap = 16 + max(margin 16, 8-slack, k/8=2) -> 32.
        #   staged_corpus = 1 chunk * 20000 * 32 * 4      = 2_560_000
        #   labels_ids    = 20000 * 8                     =   160_000
        #   query_blocks  = 1000 * 32 * 4                 =   128_000
        #   topk_carries  = 2 * 1000 * 32 * 12            =   768_000
        m = memwatch.single_engine_model(20000, 1000, 32, 16)
        assert m["select"] == "topk" and m["kcap"] == 32
        assert m["terms"]["staged_corpus"] == 2_560_000
        assert m["terms"]["labels_ids"] == 160_000
        assert m["terms"]["query_blocks"] == 128_000
        assert m["terms"]["topk_carries"] == 768_000
        assert m["total_bytes"] == 3_616_000

    def test_single_sort_path_hand_computed(self):
        # Small dataset -> "sort": whole-dataset staging. n=1000 a=16
        # q=100 k=4: data_block = fit_blocks(1000, 2048, 8) = 1000
        # (single block), npad=1000; kcap = 4 + margin 16 -> 24
        # (round_up(20,8)=24... resolve: kmax+extra=4+16=20 -> 24).
        # qpad = round_up(100, min(1024, 104)) with qb=min(1024,104)=104
        # -> qpad=104.
        from dmlp_tpu.config import EngineConfig
        m = memwatch.single_engine_model(1000, 100, 16, 4,
                                         config=EngineConfig())
        assert m["select"] == "sort"
        assert m["terms"]["staged_corpus"] == 1000 * 16 * 4
        assert m["terms"]["labels_ids"] == 1000 * 8
        assert m["terms"]["query_blocks"] == m["qpad"] * 16 * 4
        assert m["total_bytes"] == sum(m["terms"].values())

    def test_single_extract_path_structure(self):
        # use_pallas -> extract select; kcap <= 512 single-pass:
        # carries are double-buffered od/oi (8 B/slot).
        from dmlp_tpu.config import EngineConfig
        m = memwatch.single_engine_model(
            200_000, 10_000, 64, 32,
            config=EngineConfig(use_pallas=True))
        assert m["select"] == "extract" and not m["multipass"]
        qpad = m["qpad"]
        assert m["terms"]["topk_carries"] == 2 * qpad * m["kcap"] * 8
        assert m["terms"]["labels_ids"] == 200_000 * 4
        assert m["total_bytes"] == sum(m["terms"].values())

    def test_mesh_model_allgather_vs_ring_merge_asymmetry(self):
        # Same shape, same mesh: the all-gather merge buffer scales
        # with the data-axis size, the ring's accumulator does not —
        # the ring engine's reason to exist, as a modeled number.
        kw = dict(n=100_000, nq=5_000, na=64, kmax=32,
                  mesh_shape=(4, 2))
        ms = memwatch.mesh_engine_model(mode="sharded", **kw)
        mr = memwatch.mesh_engine_model(mode="ring", **kw)
        assert ms["per_device"] and mr["per_device"]
        assert ms["terms"]["merge_buffer"] == \
            4 * ms["q_local"] * ms["kcap"] * 12
        assert mr["terms"]["merge_buffer"] == \
            2 * mr["q_local"] * mr["kcap"] * 12
        assert ms["total_bytes"] > mr["total_bytes"]

    def test_train_model_hand_computed(self):
        # dims (64, 256, 10), batch 512, adam, mesh (1, 1):
        # params = 64*256+256 + 256*10+10 = 16640+2570 = 19210 -> x4 B
        m = memwatch.train_step_model((64, 256, 10), 512,
                                      optimizer="adam")
        pbytes = 19210 * 4
        assert m["terms"]["params"] == pbytes
        assert m["terms"]["grads"] == pbytes
        assert m["terms"]["opt_moments"] == 2 * pbytes
        assert m["terms"]["batch"] == 512 * 65 * 4
        assert m["terms"]["activations"] == 512 * (256 + 10) * 4
        assert m["total_bytes"] == sum(m["terms"].values())

    def test_resident_bytes_model_dispatch(self):
        with pytest.raises(ValueError, match="unknown workload"):
            memwatch.resident_bytes_model("warp-drive")

    def test_reconcile_marker_and_tolerance(self):
        model = {"total_bytes": 1000}
        rec = memwatch.reconcile(model, {"unavailable": "no basis"})
        assert rec["mem_stats_unavailable"] == "no basis"
        ok = memwatch.reconcile(model, {"bytes": 1500,
                                        "basis": "memory_stats"})
        assert ok["within_tolerance"] and ok["ratio"] == 1.5
        off = memwatch.reconcile(model, {"bytes": 10_000,
                                         "basis": "memory_stats"})
        assert not off["within_tolerance"]
        # live_arrays basis has its own (looser) documented bounds
        live = memwatch.reconcile(model, {"bytes": 3500,
                                          "basis": "live_arrays"})
        assert live["within_tolerance"]

    def test_reconcile_scales_per_device_model(self):
        # Measured bases are process-wide sums over devices: a healthy
        # 8-device mesh run must not read as 8x over model.
        model = {"total_bytes": 1000, "per_device": True, "n_devices": 8}
        rec = memwatch.reconcile(model, {"bytes": 8000,
                                         "basis": "live_arrays"})
        assert rec["model_bytes"] == 8000
        assert rec["model_bytes_per_device"] == 1000
        assert rec["n_devices"] == 8
        assert rec["within_tolerance"] and rec["ratio"] == 1.0
        mesh = memwatch.mesh_engine_model(100_000, 5_000, 64, 32,
                                          (4, 2))
        assert mesh["n_devices"] == 8


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = FlightRecorder(capacity=16)
        for i in range(100):
            fr.record("event", "e", i=i)
        evs = fr.events()
        assert len(evs) == 16
        assert evs[-1]["data"]["i"] == 99     # most recent survive

    def test_dump_contains_metrics_and_resilience(self, tmp_path):
        telemetry.REGISTRY.counter("d.hits").inc(2)
        fr = FlightRecorder()
        fr.record("span", "cli.solve", dur_ms=12.5)
        path = fr.dump(str(tmp_path), "unit_test")
        doc = json.load(open(path))
        assert doc["reason"] == "unit_test"
        assert doc["events"][0]["name"] == "cli.solve"
        assert doc["metrics"]["d.hits"]["total"] == 2
        assert "resilience" in doc

    def test_injected_fatal_fault_dumps_last_spans(self, tmp_path):
        """The satellite contract: a fatal-classified fault inside the
        retry layer dumps a flight artifact carrying the last N spans
        recorded before the fault."""
        from dmlp_tpu.obs.trace import span as obs_span
        from dmlp_tpu.resilience import retry as rs_retry

        s = telemetry.start(flight_dir=str(tmp_path),
                            handle_signals=False)
        try:
            for i in range(5):
                with obs_span(f"unit.phase{i}"):
                    pass

            def boom():
                raise RuntimeError("irrecoverable corruption")  # fatal

            with pytest.raises(RuntimeError):
                rs_retry.call_with_retry(boom, "unit.site")
        finally:
            s.close()
        flights = [f for f in os.listdir(tmp_path)
                   if f.startswith("FLIGHT_fatal_fault")]
        assert flights, "fatal fault left no flight artifact"
        doc = json.load(open(tmp_path / flights[0]))
        span_names = [e["name"] for e in doc["events"]
                      if e["kind"] == "span"]
        assert [f"unit.phase{i}" for i in range(5)] == span_names[-6:-1] \
            or all(f"unit.phase{i}" in span_names for i in range(5))
        fault = [e for e in doc["events"] if e["kind"] == "fault"]
        assert fault and fault[-1]["data"]["classification"] == "fatal"

    def test_retries_exhausted_transient_dumps_too(self, tmp_path):
        from dmlp_tpu.resilience import retry as rs_retry
        from dmlp_tpu.resilience.inject import InjectedTransientError

        s = telemetry.start(flight_dir=str(tmp_path),
                            handle_signals=False)
        try:
            def flaky():
                raise InjectedTransientError("injected transient")

            with pytest.raises(InjectedTransientError):
                rs_retry.call_with_retry(flaky, "unit.site",
                                         sleep=lambda _t: None)
        finally:
            s.close()
        assert any(f.startswith("FLIGHT_fatal_fault")
                   for f in os.listdir(tmp_path))

    def test_oom_records_event_but_no_dump(self, tmp_path):
        # oom belongs to the degradation ladder: recovery, not death.
        from dmlp_tpu.resilience import retry as rs_retry
        from dmlp_tpu.resilience.inject import SimulatedResourceExhausted

        s = telemetry.start(flight_dir=str(tmp_path),
                            handle_signals=False)
        try:
            def oom():
                raise SimulatedResourceExhausted("RESOURCE_EXHAUSTED")

            with pytest.raises(SimulatedResourceExhausted):
                rs_retry.call_with_retry(oom, "unit.site")
            kinds = [e["kind"] for e in s.flight.events()]
            assert "fault" in kinds
        finally:
            s.close()
        assert not any(f.startswith("FLIGHT_")
                       for f in os.listdir(tmp_path))


# ---------------------------------------------------------------------------
# session + span bridge + registry-backed resilience counters
# ---------------------------------------------------------------------------


class TestSession:
    def test_span_latencies_without_tracer(self):
        from dmlp_tpu.obs.trace import span as obs_span
        s = telemetry.start(handle_signals=False)
        try:
            with obs_span("unit.work"):
                pass
            h = telemetry.REGISTRY.get("unit.work.ms")
            assert h is not None and h.count == 1
            assert telemetry.REGISTRY.get("span.latency_ms").count == 1
        finally:
            s.close()

    def test_snapshot_file_rewritten_and_valid(self, tmp_path):
        path = str(tmp_path / "t.prom")
        s = telemetry.start(path=path, handle_signals=False)
        telemetry.REGISTRY.counter("unit.c").inc()
        s.close()                      # close writes the final snapshot
        text = open(path).read()
        assert validate_openmetrics(text) == []
        assert "unit_c_total 1" in text

    def test_session_restart_replaces(self):
        a = telemetry.start(handle_signals=False)
        b = telemetry.start(handle_signals=False)
        assert telemetry.session() is b
        assert a._closed
        b.close()
        assert telemetry.session() is None

    def test_resilience_counters_live_in_registry(self):
        from dmlp_tpu.resilience import stats as rs_stats
        rs_stats.reset()
        rs_stats.record_retry("single.stage_put")
        rs_stats.record_retry("single.stage_put")
        rs_stats.record_degradation("fused", "tuned")
        rs_stats.record_rollback()
        # one source of truth: the registry counters ARE the snapshot
        assert telemetry.REGISTRY.counter(
            "resilience.retries").total() == 2
        snap = rs_stats.snapshot()
        assert snap["retries"] == 2
        assert snap["retry_sites"] == {"single.stage_put": 2}
        assert snap["degradations"] == ["fused->tuned"]
        assert snap["rollbacks"] == 1
        assert rs_stats.any_activity()
        rs_stats.reset()
        assert not rs_stats.any_activity()
        assert rs_stats.snapshot()["retries"] == 0

    def test_snapshot_record_is_ledger_ingestible(self, tmp_path):
        from dmlp_tpu.obs.ledger import ingest_file
        s = telemetry.start(handle_signals=False)
        try:
            telemetry.REGISTRY.counter("unit.solves").inc(4)
            telemetry.REGISTRY.histogram("unit.ms").observe(5.0)
            rec = s.snapshot_record()
        finally:
            s.close()
        assert rec.kind == "telemetry"
        path = str(tmp_path / "TEL_r99.jsonl")
        rec.append_jsonl(path)
        entry = ingest_file(path)
        assert entry["status"] == "parsed"
        series = {p["series"] for p in entry["points"]}
        assert "telemetry/unit_solves_total" in series
        assert "telemetry/unit_ms_p50" in series


# ---------------------------------------------------------------------------
# engine + CLI integration
# ---------------------------------------------------------------------------


def _tiny_input(n=96, q=8, a=4, seed=0):
    from io import StringIO

    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input
    text = generate_input_text(n, q, a, 0.0, 10.0, 1, 4, 3, seed=seed)
    return parse_input(StringIO(text))


class TestEngineIntegration:
    def test_engine_publishes_model_under_session(self):
        from dmlp_tpu.config import EngineConfig
        from dmlp_tpu.engine.single import SingleChipEngine
        inp = _tiny_input()
        eng = SingleChipEngine(EngineConfig())
        s = telemetry.start(handle_signals=False)
        try:
            eng.run(inp)
            assert eng.last_mem_model is not None
            assert eng.last_mem_model["total_bytes"] > 0
            assert telemetry.REGISTRY.gauge(
                "mem.model.resident_bytes").value() == \
                eng.last_mem_model["total_bytes"]
        finally:
            s.close()

    def test_engine_model_absent_without_session(self):
        from dmlp_tpu.config import EngineConfig
        from dmlp_tpu.engine.single import SingleChipEngine
        inp = _tiny_input()
        eng = SingleChipEngine(EngineConfig())
        eng.run(inp)
        assert eng.last_mem_model is None

    def test_results_identical_with_and_without_session(self):
        from dmlp_tpu.config import EngineConfig
        from dmlp_tpu.engine.single import SingleChipEngine
        from dmlp_tpu.io.report import format_results
        inp = _tiny_input(seed=3)
        plain = format_results(SingleChipEngine(EngineConfig()).run(inp))
        s = telemetry.start(handle_signals=False)
        try:
            observed = format_results(
                SingleChipEngine(EngineConfig()).run(inp))
        finally:
            s.close()
        assert plain == observed

    def test_sharded_engine_publishes_per_device_model(self):
        from dmlp_tpu.config import EngineConfig
        from dmlp_tpu.engine.sharded import ShardedEngine
        inp = _tiny_input(n=128, q=16)
        eng = ShardedEngine(EngineConfig(mode="sharded"))
        s = telemetry.start(handle_signals=False)
        try:
            eng.run(inp)
            assert eng.last_mem_model is not None
            assert eng.last_mem_model.get("per_device")
        finally:
            s.close()


class TestCLIIntegration:
    def test_cli_telemetry_flag_end_to_end(self, tmp_path):
        from io import StringIO

        from dmlp_tpu.cli import main as cli_main
        from dmlp_tpu.io.datagen import generate_input_text
        text = generate_input_text(96, 8, 4, 0.0, 10.0, 1, 4, 3, seed=1)
        tel = str(tmp_path / "t.prom")
        met = str(tmp_path / "m.jsonl")
        out_plain, err = StringIO(), StringIO()
        rc = cli_main([], stdin=StringIO(text), stdout=out_plain,
                      stderr=err)
        assert rc == 0
        out_tel, err2 = StringIO(), StringIO()
        rc = cli_main(["--telemetry", tel, "--metrics", met],
                      stdin=StringIO(text), stdout=out_tel, stderr=err2)
        assert rc == 0
        # contract channel byte-identical with telemetry on
        assert out_plain.getvalue() == out_tel.getvalue()
        assert validate_openmetrics(open(tel).read()) == []
        summary = [json.loads(ln) for ln in open(met)
                   if json.loads(ln).get("event") == "summary"][0]
        mem = summary["mem"]
        assert mem["model_bytes"] > 0
        # CPU backend: either the live_arrays basis reconciled, or the
        # explicit marker — never silence.
        assert "mem_stats_unavailable" in mem or "basis" in mem
