"""Golden-model tests: the NumPy oracle vs an independent naive solver.

The naive solver below is a deliberately dumb per-query transcription of the
intended engine.cpp semantics (select comparator engine.cpp:251-254, vote
:320-332, report sort :334-338) so the vectorized golden model is itself
differentially tested.
"""

import collections

import numpy as np
import pytest

from dmlp_tpu.golden.reference import knn_golden, vote
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text


def naive_solve(inp: KNNInput):
    out = []
    for qi in range(inp.params.num_queries):
        k = int(inp.ks[qi])
        cands = []
        for di in range(inp.params.num_data):
            d = float(((inp.query_attrs[qi] - inp.data_attrs[di]) ** 2).sum())
            cands.append((d, int(inp.labels[di]), di))
        # selection order: dist asc, id desc (the MEASURED oracle-binary
        # comparator — label-free; golden.reference docstring)
        cands.sort(key=lambda t: (t[0], -t[2]))
        sel = cands[:k]
        counts = collections.Counter(lab for _, lab, _ in sel)
        pred = max(counts.items(), key=lambda kv: (kv[1], kv[0]))[0] if sel else -1
        # report order: dist asc, id desc
        rep = sorted(sel, key=lambda t: (t[0], -t[2]))
        ids = [i for _, _, i in rep] + [-1] * (k - len(rep))
        out.append((pred, ids))
    return out


def make_input(labels, data, ks, queries):
    data = np.asarray(data, np.float64)
    queries = np.asarray(queries, np.float64)
    return KNNInput(Params(len(labels), len(ks), data.shape[1]),
                    np.asarray(labels, np.int32), data,
                    np.asarray(ks, np.int32), queries)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_golden_matches_naive_random(seed):
    text = generate_input_text(60, 20, 5, -3, 3, 1, 10, 4, seed=seed)
    inp = parse_input_text(text)
    golden = knn_golden(inp, query_block=7)  # odd block to exercise blocking
    naive = naive_solve(inp)
    for r, (pred, ids) in zip(golden, naive):
        assert r.predicted_label == pred
        assert list(r.neighbor_ids) == ids


def test_tie_breaking_duplicate_points():
    # Four identical points: distance ties everywhere. Selection is
    # LABEL-FREE (dist asc, id desc) — verified against the actual oracle
    # binary bench_1 run in-container on THIS input (r5 tie-semantics
    # measurement): it selects ids [3, 2]; vote ties 0-vs-3 -> larger
    # label 3; checksum below is bench_1's own output.
    inp = make_input(labels=[1, 3, 3, 0],
                     data=[[0.0], [0.0], [0.0], [0.0]],
                     ks=[2], queries=[[0.0]])
    (r,) = knn_golden(inp)
    assert list(r.neighbor_ids) == [3, 2]
    assert r.predicted_label == 3
    assert r.checksum() == 10328283706273687613  # bench_1, measured
    naive = naive_solve(inp)
    assert (r.predicted_label, list(r.neighbor_ids)) == naive[0]


def test_vote_tie_prefers_larger_label():
    inp = make_input(labels=[5, 2, 5, 2],
                     data=[[0.0], [1.0], [2.0], [3.0]],
                     ks=[4], queries=[[0.0]])
    (r,) = knn_golden(inp)
    assert r.predicted_label == 5
    assert list(r.neighbor_ids) == [0, 1, 2, 3]


def test_equidistant_pair_report_order():
    # Query at 0, points at ±1: equal distance; larger id first in report.
    inp = make_input(labels=[0, 0], data=[[1.0], [-1.0]],
                     ks=[2], queries=[[0.0]])
    (r,) = knn_golden(inp)
    assert list(r.neighbor_ids) == [1, 0]


def test_k_exceeds_num_data_pads_with_sentinel():
    inp = make_input(labels=[2], data=[[0.0]], ks=[3], queries=[[1.0]])
    (r,) = knn_golden(inp)
    assert list(r.neighbor_ids) == [0, -1, -1]
    assert r.predicted_label == 2
    assert np.isinf(r.neighbor_dists[1])
    # checksum folds sentinels as 0 (+1) — must not raise
    assert isinstance(r.checksum(), int)


def test_vote_empty():
    assert vote(np.array([], np.int64)) == -1
