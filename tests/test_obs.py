"""Tests for the unified observability subsystem (dmlp_tpu.obs).

Covers the four modules plus their wiring: the span tracer's Chrome-trace
JSON round-trips with well-formed ph/ts/dur events and nested spans nest;
cost counters resolve real FLOPs/bytes on backends with a cost model and
fall back to the explicit ``counters_unavailable`` marker otherwise;
collective-traffic accounting matches hand-computed byte counts for a
2x2 mesh; RunRecord round-trips with its schema guard; the hardened
MetricsLogger (context manager, monotonic t_ms, clear serialization
errors); the CLI ``--trace``/``--metrics`` path via a real subprocess
(contract channels byte-identical); and the ADVICE r5 multi-pass
full-array tiling guard.
"""

import json
import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlp_tpu.obs import comms as obs_comms
from dmlp_tpu.obs import counters as obs_counters
from dmlp_tpu.obs import trace as obs_trace
from dmlp_tpu.obs.run import SCHEMA_VERSION, RunRecord
from dmlp_tpu.utils.metrics_log import MetricsLogger


# ---------------------------------------------------------------------------
# obs.trace
# ---------------------------------------------------------------------------

def test_trace_json_roundtrip_well_formed(tmp_path):
    tracer = obs_trace.Tracer()
    with tracer.span("outer", shape=[2, 3]):
        with tracer.span("inner"):
            pass
    tracer.instant("marker", n=1)
    tracer.counter("queue", depth=4)
    path = str(tmp_path / "t.json")
    tracer.write(path)

    doc = json.loads(open(path).read())
    events = doc["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    assert {e["name"] for e in spans} == {"outer", "inner"}
    for e in spans:
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert "pid" in e and "tid" in e
    assert any(e.get("ph") == "i" and e["name"] == "marker" for e in events)
    assert any(e.get("ph") == "C" and e["args"]["depth"] == 4.0
               for e in events)
    # args survive the round trip
    outer = next(e for e in spans if e["name"] == "outer")
    assert outer["args"]["shape"] == [2, 3]


def test_trace_nested_spans_nest():
    """A child span's [ts, ts+dur) interval sits inside its parent's."""
    tracer = obs_trace.Tracer()
    with tracer.span("parent"):
        with tracer.span("child"):
            pass
    evs = {e["name"]: e for e in tracer.to_dict()["traceEvents"]
           if e.get("ph") == "X"}
    p, c = evs["parent"], evs["child"]
    assert p["ts"] <= c["ts"]
    assert c["ts"] + c["dur"] <= p["ts"] + p["dur"] + 1e-6
    assert p["tid"] == c["tid"]


def test_trace_span_fence_blocks_device_value():
    tracer = obs_trace.Tracer()
    with tracer.span("fenced") as sp:
        out = jax.jit(lambda x: x * 2)(jnp.arange(8))
        sp.fence(out)
    (ev,) = [e for e in tracer.to_dict()["traceEvents"]
             if e.get("ph") == "X"]
    assert ev["name"] == "fenced" and ev["dur"] >= 0


def test_trace_module_hook_noop_when_uninstalled():
    assert obs_trace.active() is None
    sp = obs_trace.span("anything", x=1)
    assert sp is obs_trace.NULL_SPAN
    with sp as s:
        s.set(y=2)
        s.fence(object())
    obs_trace.instant("nothing")  # must not raise


def test_trace_install_uninstall_and_thread_tids():
    tracer = obs_trace.install(obs_trace.Tracer())
    try:
        with obs_trace.span("main-thread"):
            pass

        def worker():
            with obs_trace.span("worker-thread"):
                pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    finally:
        obs_trace.uninstall()
    evs = {e["name"]: e for e in tracer.to_dict()["traceEvents"]
           if e.get("ph") == "X"}
    assert evs["main-thread"]["tid"] != evs["worker-thread"]["tid"]
    assert obs_trace.active() is None


# ---------------------------------------------------------------------------
# obs.counters
# ---------------------------------------------------------------------------

def test_normalize_cost_shapes():
    assert obs_counters.normalize_cost(None) is None
    assert obs_counters.normalize_cost([]) is None
    assert obs_counters.normalize_cost("nope") is None
    assert obs_counters.normalize_cost({"flops": 0.0}) is None
    got = obs_counters.normalize_cost(
        [{"flops": 4.0, "bytes accessed": 8.0}])
    assert got == {"flops": 4.0, "bytes_accessed": 8.0}


def test_cost_probe_counts_jitted_matmul():
    """On the CPU backend XLA reports real flops; a (64, 32) @ (32, 64)
    matmul must count >= 2*64*32*64 of them, times the dispatch count."""
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.zeros((64, 32), jnp.float32)
    b = jnp.zeros((32, 64), jnp.float32)
    probe = obs_counters.CostProbe()
    probe.record(f, (a, b), count=3, site="test.matmul")
    got = probe.collect()
    if got.get("counters_unavailable"):
        pytest.skip("backend exposes no cost model")
    assert got["flops"] >= 3 * 2 * 64 * 32 * 64
    assert got["bytes_accessed"] > 0
    assert got["dispatches_recorded"] == 3
    assert got["per_site"]["test.matmul"]["dispatches"] == 3


def test_cost_probe_dedupes_identical_signatures():
    f = jax.jit(lambda a: a + 1)
    a = jnp.zeros((8,), jnp.float32)
    probe = obs_counters.CostProbe()
    probe.record(f, (a,), site="s")
    probe.record(f, (a,), site="s")
    assert len(probe._entries) == 1
    assert next(iter(probe._entries.values()))[3] == 2


def test_cost_probe_falls_back_cleanly():
    """Unanalyzable dispatches (a plain Python callable has no .lower)
    yield the explicit counters_unavailable marker, not an exception —
    the CPU/Pallas fallback contract."""
    probe = obs_counters.CostProbe()
    probe.record(lambda x: x, (jnp.zeros((4,)),), count=2, site="opaque")
    got = probe.collect()
    assert got["counters_unavailable"] is True
    assert got["dispatches_recorded"] == 2


def test_counters_module_hook():
    assert obs_counters.active() is None
    obs_counters.record_dispatch(None, ())  # uninstalled: no-op
    probe = obs_counters.install()
    try:
        f = jax.jit(lambda a: a * a)
        obs_counters.record_dispatch(f, (jnp.ones((4,)),), site="hook")
        assert len(probe._entries) == 1
    finally:
        obs_counters.uninstall()
    assert obs_counters.active() is None


def test_roofline_summary_fields():
    rl = obs_counters.roofline(2e9, 1e9, elapsed_s=0.5, n_chips=1)
    assert rl["achieved_flops_per_s"] == pytest.approx(4e9)
    assert rl["arithmetic_intensity"] == pytest.approx(2.0)
    if "peak_flops_per_chip" in rl:
        assert rl["utilization_vs_peak"] > 0


# ---------------------------------------------------------------------------
# obs.comms — hand-computed bytes for a 2x2 mesh
# ---------------------------------------------------------------------------

def test_allgather_traffic_2x2_hand_computed():
    # 2x2 mesh: data axis r=2, query axis c=2. Per cell: q_local=4, k=8.
    # TopK triple = 12 B/candidate -> payload = 4*8*12 = 384 B.
    # all_gather: each cell sends/receives the other (r-1)=1 cell's 384 B.
    # Per-column merge -> n_groups = c = 2.
    t = obs_comms.allgather_topk_traffic(2, 4, 8, n_groups=2)
    assert t.bytes_out_per_device == 384
    assert t.bytes_in_per_device == 384
    # total = out_per_device * r * groups = 384 * 2 * 2
    assert t.bytes_total == 1536
    assert t.axis == "data" and t.axis_size == 2


def test_ring_traffic_matches_allgather_bytes_2x2():
    ag = obs_comms.allgather_topk_traffic(2, 4, 8, n_groups=2)
    ring = obs_comms.ring_topk_traffic(2, 4, 8, n_groups=2)
    # r=2: one ppermute hop of the 384 B accumulator — same wire bytes.
    assert ring.bytes_out_per_device == ag.bytes_out_per_device == 384
    assert ring.bytes_total == ag.bytes_total == 1536


def test_ring_traffic_hops_scale():
    t = obs_comms.ring_topk_traffic(4, 4, 8)  # 3 hops x 384 B
    assert t.bytes_out_per_device == 3 * 384


def test_psum_traffic_ring_bound():
    t = obs_comms.psum_traffic(1000, 4)
    assert t.bytes_out_per_device == 1500  # 2*(4-1)/4 * 1000
    assert obs_comms.psum_traffic(1000, 1).bytes_out_per_device == 0


def test_moe_a2a_traffic_hand_computed():
    # ep=2, capacity=3, hidden=8, f32: send buffer 2*3*8*4 = 192 B,
    # meta 2*3*4 = 24 B; three a2a ops move (2*192 + 24) * 1/2 = 204 B
    # off-device per cell.
    t = obs_comms.moe_a2a_traffic(2, 3, 8)
    assert t.bytes_out_per_device == 204


def test_tp_psum_activation_traffic_hand_computed():
    # tp=4, (rows=8, hidden=16) f32 block = 512 B; ring all-reduce moves
    # 2*(4-1)/4 * 512 = 768 B per psum; 2 pairs x 3 ticks = 6 psums.
    t = obs_comms.tp_psum_activation_traffic(4, 8, 16, n_pairs=2,
                                             ticks=3)
    assert t.bytes_out_per_device == 768 * 6
    assert t.axis == "tp"
    assert obs_comms.tp_psum_activation_traffic(
        1, 8, 16).bytes_out_per_device == 0  # single tp cell: no wire


def test_ep_psum_combine_traffic_hand_computed():
    # ep=2, (tokens=16, hidden=8) f32 partials = 512 B; ring bound
    # 2*(2-1)/2 * 512 = 512 B per device per step.
    t = obs_comms.ep_psum_combine_traffic(2, 16, 8)
    assert t.bytes_out_per_device == 512
    assert t.collective == "psum_ep_combine"


def test_train_step_comms_dense_moe_and_pp3_tp():
    # Dense MoE: the ep combine psum record rides moe_dense.
    out = obs_comms.train_step_comms(
        0, (2, 2), steps=3, moe_dense={"ep": 2, "tokens": 16,
                                       "hidden": 8})
    kinds = [t.collective for t in out]
    assert "psum_ep_combine" in kinds
    ep = next(t for t in out if t.collective == "psum_ep_combine")
    assert ep.count == 3 and ep.n_groups == 2  # per step, per dp group

    # dp_pp3: pipeline dict with tp adds the per-pair activation psum
    # next to the ppermute record (fwd+bwd -> count 2*steps).
    out = obs_comms.train_step_comms(
        1000, (2, 2, 2), steps=5,
        pipeline={"pp": 2, "n_micro": 4, "micro_rows": 8, "hidden": 16,
                  "tp": 2, "n_pairs": 2, "n_groups": 4})
    kinds = [t.collective for t in out]
    assert "ppermute_pipeline" in kinds and "psum_tp_activations" in kinds
    tp = next(t for t in out if t.collective == "psum_tp_activations")
    # ticks = n_micro + pp - 1 = 5; groups = dp*pp = 4; fwd+bwd count.
    assert tp.count == 10 and tp.n_groups == 4
    assert tp.bytes_out_per_device == \
        round(2 * (2 - 1) * 8 * 16 * 4 / 2) * 2 * 5


def test_every_hand_written_collective_site_has_a_live_model():
    """The static analyzer's R1 coverage check, exercised as a test:
    every traffic-bearing collective call site in engine/parallel/train
    carries a comms-model annotation naming a function that exists in
    obs/comms.py (R103/R104 both empty on the real tree)."""
    from dmlp_tpu.check.analyzer import analyze_package
    r1 = [f for f in analyze_package(["R1"])
          if f.rule in ("R103", "R104")]
    assert r1 == []


def test_engine_comms_from_dispatch_shapes():
    single = obs_comms.engine_comms("allgather", (1, 4), 16, 8)
    assert single == []  # data axis of 1: no cross-shard merge
    (t,) = obs_comms.engine_comms("ring", (2, 2), 4, 8)
    assert t.collective == "ring_allreduce_topk"
    assert t.bytes_total == 1536  # matches the hand-computed 2x2 case
    summary = obs_comms.summarize([t])
    assert summary["bytes_total"] == 1536
    assert summary["bytes_by_axis"] == {"data": 1536}


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 virtual devices")
@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="this jax lacks jax.shard_map (mesh engines "
                           "unavailable, same skip as the seed suite)")
def test_sharded_engine_records_comms_for_solved_shapes():
    """The mesh engine's last_comms must reflect the dispatched merge:
    validated against the shapes the solve actually used."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.sharded import ShardedEngine
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text

    inp = parse_input_text(
        generate_input_text(600, 40, 8, 0.0, 50.0, 1, 6, 4, seed=11))
    eng = ShardedEngine(EngineConfig(mode="sharded", mesh_shape=(2, 2)))
    eng.run(inp)
    assert eng.last_comms, "mesh solve must account its merge traffic"
    (t,) = eng.last_comms
    r, c = eng.mesh.devices.shape
    assert (t.axis_size, t.n_groups) == (r, c)
    assert t.collective == "all_gather_merge_topk" and t.axis == "data"
    # payload derives from the dispatched (q_local, k) candidate triple:
    # per-device bytes must be a whole number of 12 B candidates from the
    # (r-1) peer cells.
    assert t.bytes_out_per_device % ((r - 1) * 12) == 0
    assert t.bytes_out_per_device > 0


# ---------------------------------------------------------------------------
# obs.run — RunRecord
# ---------------------------------------------------------------------------

def test_runrecord_roundtrip(tmp_path):
    rec = RunRecord(kind="bench", tool="test", config={"n": 4},
                    metrics={"ms": 1.5}, artifacts={"trace": "t.json"})
    path = str(tmp_path / "rec.json")
    rec.write(path)
    back = RunRecord.load(path)
    assert back.schema == SCHEMA_VERSION
    assert back.config == {"n": 4} and back.metrics == {"ms": 1.5}
    assert back.artifacts == {"trace": "t.json"}
    assert back.host.get("python")


def test_runrecord_jsonl_append(tmp_path):
    path = str(tmp_path / "runs.jsonl")
    RunRecord(kind="a", tool="t").append_jsonl(path)
    RunRecord(kind="b", tool="t").append_jsonl(path)
    lines = open(path).read().splitlines()
    assert [json.loads(ln)["kind"] for ln in lines] == ["a", "b"]


def test_runrecord_schema_guard_and_serialization_error():
    with pytest.raises(ValueError, match="newer"):
        RunRecord.from_dict({"kind": "x", "tool": "t",
                             "schema": SCHEMA_VERSION + 1})
    bad = RunRecord(kind="x", tool="t", metrics={"arr": np.zeros(2)})
    with pytest.raises(TypeError, match="non-JSON-serializable"):
        bad.to_json()


# ---------------------------------------------------------------------------
# utils.metrics_log hardening
# ---------------------------------------------------------------------------

def test_metrics_logger_context_manager_and_t_ms(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path=path) as log:
        log.log(step=1)
        log.log(step=2)
    assert log._fh.closed
    recs = [json.loads(ln) for ln in open(path).read().splitlines()]
    assert [r["step"] for r in recs] == [1, 2]
    assert all("t_ms" in r for r in recs)
    assert recs[0]["t_ms"] <= recs[1]["t_ms"]  # monotonic


def test_metrics_logger_clear_error_on_unserializable(tmp_path):
    with MetricsLogger(path=str(tmp_path / "m.jsonl")) as log:
        with pytest.raises(TypeError, match=r"bad_key"):
            log.log(bad_key=np.zeros(3), fine=1)


# ---------------------------------------------------------------------------
# ADVICE r5: multi-pass extract full-array tiling guard
# ---------------------------------------------------------------------------

def _widek_input(n=60_000, nq=128, na=8, k=600):
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text
    return parse_input_text(
        generate_input_text(n, nq, na, 0.0, 100.0, k, k, 4, seed=3))


def test_multipass_full_array_supports_invariant_holds_today():
    """The carry-over the guard protects: today chunk-level tileability
    implies full-array tileability (divisibility by 128*ne survives
    multiplication). If this fails, the kernel variants changed and
    the multi-pass driver needs a real fallback."""
    from dmlp_tpu.ops.pallas_extract import supports
    assert supports(128, 38400, 8, 512)
    assert supports(128, 2 * 38400, 8, 512)


def test_multipass_guard_trips_when_full_array_untileable(monkeypatch):
    """Regression for the new guard: if the kernel resolution ever
    rejects the concatenated d_full row count while accepting the chunk
    size, the multi-pass driver must fail loudly BEFORE dispatching
    passes 2+ over a shape no kernel can tile (previously it dispatched
    anyway). The driver resolves fused-vs-two-pass through
    pallas_fused.resolve_topk_kernel (ISSUE 8) — that is the seam the
    guard actually consults, so that is what the fake rejects."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.ops import pallas_fused

    inp = _widek_input()
    eng = SingleChipEngine(EngineConfig(use_pallas=True, select="extract"))

    real = pallas_fused.resolve_topk_kernel
    chunk_sizes = []

    def fake_resolve(qb, b, a, kc, rung="fused"):
        chunk_sizes.append(b)
        if b > 38400:        # the full concatenated array — reject it
            return None, None
        return real(qb, b, a, kc, rung=rung)

    monkeypatch.setattr(pallas_fused, "resolve_topk_kernel", fake_resolve)
    with pytest.raises(AssertionError, match="full-array sweep"):
        eng._solve_extract_multipass(inp)
    # the guard saw both row counts: per-chunk then full
    assert any(b <= 38400 for b in chunk_sizes)
    assert any(b > 38400 for b in chunk_sizes)


# ---------------------------------------------------------------------------
# CLI smoke: --trace / --metrics via a real subprocess
# ---------------------------------------------------------------------------

def _cli_env():
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env


@pytest.mark.slow
def test_cli_trace_metrics_subprocess_contract(tmp_path):
    """`--trace`/`--metrics` must leave stdout AND stderr byte-identical
    to an uninstrumented run while producing a Perfetto-loadable trace
    and a metrics JSONL whose summary carries counters (or the explicit
    unavailable marker) — the acceptance contract, via a real pipe."""
    from dmlp_tpu.io.datagen import generate_input_text

    text = generate_input_text(1200, 60, 8, 0.0, 50.0, 1, 8, 5, seed=9)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(*extra):
        proc = subprocess.run(
            [sys.executable, "-m", "dmlp_tpu", *extra],
            input=text.encode(), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=_cli_env(), cwd=repo, timeout=240)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        return proc.stdout, proc.stderr

    out_plain, _ = run()
    trace_path = str(tmp_path / "t.json")
    metrics_path = str(tmp_path / "m.jsonl")
    out_obs, err_obs = run("--trace", trace_path, "--metrics", metrics_path)

    assert out_obs == out_plain                      # stdout byte-identical
    assert err_obs.decode().startswith("Time taken:")
    assert len(err_obs.decode().splitlines()) == 1   # no extra stderr

    # the committed checker validates both artifacts end to end
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "check_trace.py"),
         trace_path, metrics_path],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=repo,
        timeout=60)
    assert proc.returncode == 0, proc.stderr.decode()

    doc = json.loads(open(trace_path).read())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert any(n.startswith("cli.solve") for n in names)
    assert any(n.startswith("single.") for n in names)

    recs = [json.loads(ln) for ln in open(metrics_path).read().splitlines()]
    final = recs[-1]
    assert final["event"] == "summary"
    c = final["counters"]
    assert c.get("counters_unavailable") or (
        c["flops"] > 0 and c["bytes_accessed"] > 0)


def test_cli_inprocess_trace_metrics(tmp_path):
    """Same contract in-process (fast, runs in the default suite)."""
    import io

    from dmlp_tpu.cli import main
    from dmlp_tpu.io.datagen import generate_input_text

    text = generate_input_text(300, 20, 6, 0.0, 20.0, 1, 5, 3, seed=4)
    trace_path = str(tmp_path / "t.json")
    metrics_path = str(tmp_path / "m.jsonl")

    out1, err1 = io.StringIO(), io.StringIO()
    assert main([], stdin=io.StringIO(text), stdout=out1, stderr=err1) == 0
    out2, err2 = io.StringIO(), io.StringIO()
    assert main(["--trace", trace_path, "--metrics", metrics_path],
                stdin=io.StringIO(text), stdout=out2, stderr=err2) == 0

    assert out1.getvalue() == out2.getvalue()
    assert err2.getvalue().startswith("Time taken:")
    assert obs_trace.active() is None          # hooks uninstalled
    assert obs_counters.active() is None

    doc = json.loads(open(trace_path).read())
    assert [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    final = json.loads(open(metrics_path).read().splitlines()[-1])
    assert final["event"] == "summary" and "counters" in final


def test_cli_warmup_does_not_double_counters(tmp_path):
    """--warmup runs the full solve once untimed; the probe must be reset
    after it so counters cover the TIMED region only (a doubled count
    would overstate achieved FLOP/s ~2x in the roofline)."""
    import io

    from dmlp_tpu.cli import main
    from dmlp_tpu.io.datagen import generate_input_text

    text = generate_input_text(300, 20, 6, 0.0, 20.0, 1, 5, 3, seed=4)

    def counters_for(extra):
        path = str(tmp_path / f"m{len(extra)}.jsonl")
        assert main([*extra, "--metrics", path], stdin=io.StringIO(text),
                    stdout=io.StringIO(), stderr=io.StringIO()) == 0
        return json.loads(open(path).read().splitlines()[-1])["counters"]

    plain = counters_for([])
    warm = counters_for(["--warmup"])
    if plain.get("counters_unavailable"):
        pytest.skip("backend exposes no cost model")
    assert warm["flops"] == plain["flops"]
    assert warm["dispatches_recorded"] == plain["dispatches_recorded"]
