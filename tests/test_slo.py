"""Streaming SLO engine tests: windowed quantiles vs numpy, the
Sampler drift fix, burn-rate hysteresis / flap suppression, Theil–Sen
trends, the predictive autoscale policy, the slo.alert trace contract,
and the slo/ ledger + gate plumbing."""

import json
import math
import threading

import numpy as np
import pytest

from dmlp_tpu.fleet.autoscale import (predictive_target_replicas,
                                      target_replicas)
from dmlp_tpu.obs import slo as obs_slo
from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs.ledger import (_better_direction,
                                 _runrecord_series_name)
from dmlp_tpu.obs.telemetry import Histogram, Registry

REL = telemetry.HIST_QUANTILE_REL_ERROR


class FakeClock:
    """Injectable monotonic clock for deterministic window rotation."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _windowed_hist(sub_s=1.0, max_window_s=120.0, clock=None):
    clock = clock or FakeClock()
    h = Histogram("t.lat_ms", unit="ms")
    h.enable_windows(max_window_s=max_window_s, sub_s=sub_s,
                     time_fn=clock)
    return h, clock


# ---------------------------------------------------------------------------
# windowed quantiles
# ---------------------------------------------------------------------------


def test_window_quantile_matches_numpy_within_bound():
    h, clock = _windowed_hist(sub_s=1.0)
    rng = np.random.default_rng(7)
    window = []
    # 30 s of samples, 20 per second, lognormal latencies.
    for _ in range(30):
        for v in np.exp(rng.normal(1.5, 0.6, 20)):
            h.observe(float(v))
            window.append(float(v))
        clock.advance(1.0)
    for q in (0.5, 0.95, 0.99):
        est = h.window_quantile(60.0, q)       # window covers all
        exact = float(np.percentile(window, q * 100))
        assert est == pytest.approx(exact, rel=REL + 1e-6)


def test_window_quantile_partial_window_startup():
    """A window longer than the elapsed time sees every sample — a
    cold ring must not report NaN or a truncated distribution."""
    h, clock = _windowed_hist(sub_s=1.0)
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    for v in vals:
        h.observe(v)
        clock.advance(0.1)         # only 0.5 s elapsed, window is 60 s
    snap = h.window_snapshot(60.0)
    assert snap["count"] == len(vals)
    assert snap["min"] == 1.0 and snap["max"] == 5.0
    assert snap["p50"] == pytest.approx(3.0, rel=REL + 1e-6)


def test_window_rotation_ages_out_old_samples():
    h, clock = _windowed_hist(sub_s=1.0)
    for _ in range(10):
        h.observe(100.0)           # old: all slow
        clock.advance(1.0)
    # t=10; the 10 s window still sees them
    assert h.window_snapshot(10.0)["count"] == 10
    clock.advance(20.0)            # t=30: all aged out of a 10 s window
    for _ in range(5):
        h.observe(1.0)
        clock.advance(1.0)
    snap = h.window_snapshot(10.0)
    assert snap["count"] == 5
    assert snap["max"] == 1.0      # the 100 ms outliers are GONE
    # ...while the cumulative histogram still remembers everything
    assert h.count == 15
    assert h.quantile(1.0) == 100.0


def test_window_rotation_boundary_exact_multiple():
    """Samples landing exactly on a sub-window boundary open a new
    frame (>=, not >) and the trailing-window cutoff keeps at most one
    sub-window of slack."""
    h, clock = _windowed_hist(sub_s=2.0)
    h.observe(1.0)                 # frame [0, 2)
    clock.advance(2.0)             # exactly one sub-window
    h.observe(2.0)                 # must open frame [2, 4)
    assert len(h._frames) == 2
    assert h._frames[-1].start == pytest.approx(2.0)
    clock.advance(2.0)             # t=4
    # 2 s window: cutoff 2.0 — frame [0,2) has start+sub == cutoff,
    # fully aged; frame [2,4) remains.
    assert h.window_snapshot(2.0)["count"] == 1


def test_window_idle_gap_keeps_grid_alignment():
    """An idle gap must not stretch one frame across it (stale samples
    would then never age out)."""
    h, clock = _windowed_hist(sub_s=1.0)
    h.observe(50.0)
    clock.advance(7.3)             # idle gap
    h.observe(1.0)
    # New frame starts on the 1 s grid (t=7.0), not at 0.0
    assert h._frames[-1].start == pytest.approx(7.0)
    clock.advance(0.0)
    assert h.window_snapshot(2.0)["count"] == 1    # the old one aged


def test_window_above_splits_at_bucket_resolution():
    h, clock = _windowed_hist(sub_s=1.0)
    for v in (1.0, 2.0, 50.0, 60.0, 70.0):
        h.observe(v)
    bad, total = h.window_above(30.0, 10.0)
    assert (bad, total) == (3, 5)
    # max <= threshold short-circuits exactly: all good
    assert h.window_above(30.0, 70.0) == (0, 5)
    assert h.window_above(30.0, 1e9) == (0, 5)


def test_window_apis_require_enablement():
    h = Histogram("t.plain")
    h.observe(1.0)
    assert not h.windowed
    with pytest.raises(ValueError, match="no window ring"):
        h.window_quantile(10.0, 0.5)
    with pytest.raises(ValueError, match="no window ring"):
        h.window_above(10.0, 1.0)


def test_enable_windows_idempotent_and_validates_geometry():
    h, clock = _windowed_hist(sub_s=1.0)
    h.enable_windows(sub_s=99.0)       # second call: no-op, keeps 1.0
    assert h._sub_s == 1.0
    with pytest.raises(ValueError, match="window geometry"):
        Histogram("t.bad").enable_windows(max_window_s=1.0, sub_s=2.0)
    with pytest.raises(ValueError, match="window geometry"):
        Histogram("t.bad2").enable_windows(sub_s=0.0)


def test_windowed_histogram_concurrent_observe_and_read():
    """Writers hammer observe() while readers merge windows — the
    single-lock discipline must keep every merged state consistent
    (count equals the sum of its bucket counts; no exceptions)."""
    h, clock = _windowed_hist(sub_s=0.001)   # rotate constantly
    clock_lock = threading.Lock()
    errors = []
    N, W = 2000, 4

    def writer(seed):
        rng = np.random.default_rng(seed)
        for v in np.exp(rng.normal(1.0, 0.5, N)):
            h.observe(float(v))
            with clock_lock:
                clock.advance(1e-5)

    def reader():
        try:
            for _ in range(200):
                snap = h.window_snapshot(10.0)
                assert snap["count"] >= 0
                q = h.window_quantile(10.0, 0.99)
                assert math.isnan(q) or q > 0
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(W)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert h.count == N * W
    # every observation landed in some frame
    assert sum(fr.count for fr in h._frames) <= N * W
    snap = h.window_snapshot(1e6)
    assert snap["count"] == N * W


# ---------------------------------------------------------------------------
# Sampler interval drift (the bugfix satellite)
# ---------------------------------------------------------------------------


def test_next_deadline_keeps_phase_under_slow_ticks():
    """Deadline-anchored schedule: sampling work that takes longer
    than the interval SKIPS the missed slots instead of drifting the
    phase or bursting to catch up."""
    nd = telemetry.Sampler._next_deadline
    # on-time: next deadline is exactly one interval later
    deadline, delay = nd(10.0, 10.2, 1.0)
    assert deadline == pytest.approx(11.0)
    assert delay == pytest.approx(0.8)
    # work overran by 2.7 intervals: the schedule skips to the next
    # FUTURE grid point (13.0 + 1.0 = 14.0), never a negative delay
    deadline, delay = nd(10.0, 13.7, 1.0)
    assert deadline == pytest.approx(14.0)
    assert delay == pytest.approx(0.3)
    assert deadline % 1.0 == pytest.approx(0.0)   # phase preserved


def test_next_deadline_no_drift_accumulation():
    """The old sleep-after-work loop drifted by the work time every
    tick; the grid schedule's deadlines stay exact multiples."""
    nd = telemetry.Sampler._next_deadline
    deadline = 0.0
    work = 0.13                    # per-tick work time
    now = 0.0
    fired = []
    for _ in range(50):
        now = deadline + work      # wake late by the work time
        deadline, delay = nd(deadline, now, 1.0)
        fired.append(deadline)
        assert delay >= 0.0
    # after 50 ticks the schedule is still on the integer grid —
    # zero accumulated drift (old behavior: 50 * 0.13 = 6.5 s late)
    assert fired[-1] == pytest.approx(50.0)


def test_next_deadline_never_negative_delay():
    nd = telemetry.Sampler._next_deadline
    deadline = 5.0
    for now in (5.0, 5.999, 6.0, 17.42, 1000.0):
        nxt, delay = nd(deadline, now, 0.5)
        assert nxt > now or delay == 0.0
        assert delay >= 0.0


# ---------------------------------------------------------------------------
# objective grammar + Theil–Sen
# ---------------------------------------------------------------------------


def test_parse_objective_latency_and_availability():
    o = obs_slo.parse_objective(
        "fleet.request_latency_ms p99 < 50 over 1m")
    assert o.kind == "latency"
    assert o.metric == "fleet.request_latency_ms"
    assert o.quantile == pytest.approx(0.99)
    assert o.threshold == 50.0
    assert o.window_s == 60.0
    assert o.budget == pytest.approx(0.01)
    assert o.name == "fleet.request_latency_ms:p99"
    a = obs_slo.parse_objective(
        "serve.ok/serve.total availability > 0.995 over 5m")
    assert a.kind == "availability"
    assert (a.good, a.total) == ("serve.ok", "serve.total")
    assert a.budget == pytest.approx(0.005)
    assert a.window_s == 300.0
    assert "availability" in a.describe()


def test_parse_objective_rejects_garbage():
    for bad in ("latency_ms p99 over 1m", "p99 < 50", "m q50 < 1",
                "a/b availability > 2 over 1m", ""):
        with pytest.raises(ValueError):
            obs_slo.parse_objective(bad)
    with pytest.raises(ValueError):
        obs_slo.parse_window("soon")
    assert obs_slo.parse_window("250ms") == pytest.approx(0.25)
    assert obs_slo.parse_window("2") == 2.0


def test_theil_sen_robust_and_degenerate():
    pts = [(float(i), 2.0 * i + 1.0) for i in range(10)]
    assert obs_slo.theil_sen(pts) == pytest.approx(2.0)
    # one wild outlier cannot bend the median of pairwise slopes much
    pts[5] = (5.0, 1000.0)
    assert obs_slo.theil_sen(pts) == pytest.approx(2.0, abs=0.5)
    assert math.isnan(obs_slo.theil_sen([]))
    assert math.isnan(obs_slo.theil_sen([(1.0, 2.0)]))
    assert math.isnan(obs_slo.theil_sen([(1.0, 2.0), (1.0, 3.0)]))


# ---------------------------------------------------------------------------
# burn-rate lifecycle: pure rule + live evaluator
# ---------------------------------------------------------------------------


def test_next_state_lifecycle_edges():
    ns = obs_slo.SLOEvaluator.next_state
    OK, P, F = obs_slo.OK, obs_slo.PENDING, obs_slo.FIRING
    # ok enters pending on a hot fast window, never jumps to firing
    assert ns(OK, True, True, 99, 0, 2, 3) == P
    assert ns(OK, False, False, 0, 99, 2, 3) == OK
    # pending -> firing needs BOTH windows hot AND the streak
    assert ns(P, True, True, 2, 0, 2, 3) == F
    assert ns(P, True, True, 1, 0, 2, 3) == P
    assert ns(P, True, False, 99, 0, 2, 3) == P
    # pending clears only after the good streak
    assert ns(P, False, True, 0, 3, 2, 3) == OK
    assert ns(P, False, True, 0, 2, 2, 3) == P
    # firing clears only on both-cold + streak; no firing -> pending
    assert ns(F, False, False, 0, 3, 2, 3) == OK
    assert ns(F, False, False, 0, 2, 2, 3) == F
    assert ns(F, False, True, 0, 99, 2, 3) == F
    assert ns(F, True, True, 5, 0, 2, 3) == F


def _make_eval(reg, clock, spec="svc.lat_ms p90 < 10 over 60s",
               **kw):
    kw.setdefault("fast_s", 10.0)
    kw.setdefault("sub_s", 1.0)
    kw.setdefault("for_ticks", 2)
    kw.setdefault("clear_ticks", 2)
    kw.setdefault("flight_dump", False)
    return obs_slo.SLOEvaluator([spec], reg, time_fn=clock, **kw)


def test_evaluator_breach_fires_and_recovers_one_cycle():
    reg = Registry()
    clock = FakeClock()
    ev = _make_eval(reg, clock)
    obj = "svc.lat_ms:p90"
    h = reg.get("svc.lat_ms")
    assert h is not None and h.windowed   # bound by the evaluator
    # healthy traffic: 5 fast samples per second for 20 s
    for _ in range(20):
        for _ in range(5):
            h.observe(1.0)
        ev.tick()
        clock.advance(1.0)
    assert ev.state(obj) == obs_slo.OK
    # overload: every sample blows the 10 ms threshold
    states = []
    for _ in range(6):
        for _ in range(5):
            h.observe(100.0)
        ev.tick()
        states.append(ev.state(obj))
        clock.advance(1.0)
    assert obs_slo.PENDING in states
    assert ev.state(obj) == obs_slo.FIRING
    sig = ev.signals(obj)
    assert sig["burn_fast"] > 1.0
    assert sig["burn_slow"] > 1.0
    # recovery: jump past the slow window so the bad samples age out
    clock.advance(120.0)
    for _ in range(5):
        for _ in range(5):
            h.observe(1.0)
        ev.tick()
        clock.advance(1.0)
    assert ev.state(obj) == obs_slo.OK
    assert ev.alert_cycles(obj) == 1
    seq = [(t["prev"], t["state"]) for t in ev.transitions]
    assert seq == [("ok", "pending"), ("pending", "firing"),
                   ("firing", "ok")]
    # transitions counter labeled by entered state
    c = reg.get("slo.transitions")
    assert c.value("pending") == 1.0
    assert c.value("firing") == 1.0
    assert c.value("ok") == 1.0


def test_evaluator_short_spike_parks_in_pending():
    """Flap suppression: a one-tick spike must go ok -> pending -> ok
    without EVER firing (for_ticks hysteresis)."""
    reg = Registry()
    clock = FakeClock()
    ev = _make_eval(reg, clock, for_ticks=3)
    obj = "svc.lat_ms:p90"
    h = reg.get("svc.lat_ms")
    for _ in range(15):
        for _ in range(5):
            h.observe(1.0)
        ev.tick()
        clock.advance(1.0)
    for _ in range(10):             # one bad burst, one tick
        h.observe(100.0)
    ev.tick()
    assert ev.state(obj) == obs_slo.PENDING
    clock.advance(15.0)             # the spike ages out of fast window
    for _ in range(4):
        for _ in range(5):
            h.observe(1.0)
        ev.tick()
        clock.advance(1.0)
    assert ev.state(obj) == obs_slo.OK
    states = [t["state"] for t in ev.transitions]
    assert obs_slo.FIRING not in states
    assert states == ["pending", "ok"]


def test_evaluator_availability_burn_from_counters():
    reg = Registry()
    clock = FakeClock()
    ev = _make_eval(reg, clock,
                    spec="svc.good/svc.req availability > 0.9 over 60s")
    obj = "svc.req:availability"
    good, total = reg.counter("svc.good"), reg.counter("svc.req")
    for _ in range(20):
        good.inc(10)
        total.inc(10)
        ev.tick()
        clock.advance(1.0)
    assert ev.state(obj) == obs_slo.OK
    assert ev.signals(obj)["burn_fast"] == 0.0
    for _ in range(6):              # outage: all requests fail
        total.inc(10)
        ev.tick()
        clock.advance(1.0)
    assert ev.state(obj) == obs_slo.FIRING
    assert ev.signals(obj)["burn_fast"] > 1.0


def test_evaluator_sample_fn_override_feeds_availability():
    """The router's merged-scrape hook: sample_fn replaces registry
    counter reads entirely."""
    reg = Registry()
    clock = FakeClock()
    cum = {"good": 0.0, "total": 0.0}
    obj = obs_slo.parse_objective(
        "f.good/f.total availability > 0.9 over 60s")
    obj.sample_fn = lambda: (cum["good"], cum["total"])
    ev = obs_slo.SLOEvaluator([obj], reg, fast_s=10.0, sub_s=1.0,
                              for_ticks=1, clear_ticks=1,
                              time_fn=clock, flight_dump=False)
    for _ in range(10):
        cum["good"] += 5
        cum["total"] += 10          # 50% failures, budget 10%
        ev.tick()
        clock.advance(1.0)
    assert ev.state("f.total:availability") == obs_slo.FIRING


def test_evaluator_gauges_and_openmetrics_family():
    reg = Registry()
    clock = FakeClock()
    ev = _make_eval(reg, clock)
    obj = "svc.lat_ms:p90"
    h = reg.get("svc.lat_ms")
    for _ in range(5):
        h.observe(1.0)
        ev.tick()
        clock.advance(1.0)
    assert reg.get("slo.state").value(obj) == 0.0
    assert reg.get("slo.ok").value(obj) == 1.0
    assert reg.get("slo.firing").value(obj) == 0.0
    assert reg.get("slo.burn_rate.fast").value(obj) == 0.0
    text = reg.to_openmetrics()
    assert "# TYPE slo_state gauge" in text
    assert "slo_burn_rate_fast" in text
    assert telemetry.validate_openmetrics(text) == []
    snap = ev.snapshot()
    assert snap["objectives"][obj]["state"] == "ok"
    assert snap["transitions"] == 0


def test_evaluator_trend_slope_and_projection():
    """A steadily degrading latency series yields a positive Theil–Sen
    slope and a finite projected crossing — the predictive signal."""
    reg = Registry()
    clock = FakeClock()
    ev = _make_eval(reg, clock, spec="svc.lat_ms p90 < 100 over 120s",
                    fast_s=5.0)
    obj = "svc.lat_ms:p90"
    h = reg.get("svc.lat_ms")
    lat = 10.0
    for _ in range(30):
        for _ in range(10):
            h.observe(lat)
        ev.tick()
        clock.advance(1.0)
        lat += 2.0                  # +2 ms every second, toward 100
    sig = ev.signals(obj)
    assert sig["slope_ms_per_s"] > 0.5
    assert math.isfinite(sig["projected_s"])
    assert 0.0 < sig["projected_s"] < 120.0
    assert ev.state(obj) == obs_slo.OK     # not yet breaching


def test_evaluator_duplicate_objective_names_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        obs_slo.SLOEvaluator(
            ["m.x p99 < 5 over 10s", "m.x p99 < 9 over 10s"],
            Registry())


# ---------------------------------------------------------------------------
# predictive autoscale policy (pure)
# ---------------------------------------------------------------------------


def _sig(**kw):
    base = {"burn_fast": 0.0, "burn_slow": 0.0,
            "slope_ms_per_s": 0.0, "projected_s": math.inf,
            "p_fast": 40.0, "threshold": 50.0}
    base.update(kw)
    return base


def test_predictive_scales_up_on_burn():
    assert predictive_target_replicas(_sig(burn_fast=2.0), 2, 1, 4) == 3


def test_predictive_scales_up_before_breach_on_projection():
    """The leading signal: no budget burnt YET, but the slope projects
    a crossing inside the lead time -> scale now."""
    s = _sig(slope_ms_per_s=1.5, projected_s=6.0, p_fast=41.0)
    assert s["burn_fast"] == 0.0
    assert predictive_target_replicas(s, 2, 1, 4, lead_time_s=10.0) == 3
    # projection beyond the horizon: hold
    s = _sig(slope_ms_per_s=0.1, projected_s=90.0)
    assert predictive_target_replicas(s, 2, 1, 4, lead_time_s=10.0) == 2


def test_predictive_flat_load_is_a_fixed_point():
    """Flat load in the dead band between the up and down triggers
    must never oscillate: the decision is current, every time."""
    s = _sig(p_fast=40.0)           # calm but above down_margin * 50
    cur = 2
    for _ in range(50):
        cur = predictive_target_replicas(s, cur, 1, 4)
    assert cur == 2


def test_predictive_synthetic_ramp_scales_before_reactive_would():
    """Synthetic ramp: latency climbing toward the threshold. The
    predictive policy steps up while p_fast is still under the
    threshold (burn 0); the reactive watermark policy, fed a
    per-replica load that has not yet crossed its high mark, holds —
    the lead the SLO signal buys."""
    p99, slope = 20.0, 4.0          # ms, ms/s
    cur_pred = cur_react = 1
    scaled_at_p99 = None
    for step in range(20):
        projected = (50.0 - p99) / slope if p99 < 50.0 else 0.0
        sig = _sig(slope_ms_per_s=slope, projected_s=projected,
                   p_fast=p99, burn_fast=0.0 if p99 < 50.0 else 5.0)
        nxt = predictive_target_replicas(sig, cur_pred, 1, 4,
                                         lead_time_s=6.0)
        if nxt > cur_pred and scaled_at_p99 is None:
            scaled_at_p99 = p99
        cur_pred = nxt
        # reactive arm: queue load stays under the watermark until the
        # breach is already happening
        load = [0.5 if p99 < 50.0 else 8.0] * 6
        cur_react = target_replicas(load, cur_react, 1, 4, 4.0, 0.25)
        p99 += slope
    assert scaled_at_p99 is not None and scaled_at_p99 < 50.0
    assert cur_pred >= 2            # predictive moved...
    # ...and it moved BEFORE the threshold; reactive only after
    assert cur_react >= 2           # (eventually, once breaching)


def test_predictive_scales_down_only_when_calm():
    calm = _sig(p_fast=10.0)        # well under 0.5 * 50
    assert predictive_target_replicas(calm, 3, 1, 4) == 2
    # any warmth blocks the down-step
    assert predictive_target_replicas(
        _sig(p_fast=10.0, burn_slow=0.2), 3, 1, 4) == 3
    assert predictive_target_replicas(
        _sig(p_fast=10.0, slope_ms_per_s=0.5), 3, 1, 4) == 3
    # clamped at the floor / ceiling
    assert predictive_target_replicas(calm, 1, 1, 4) == 1
    assert predictive_target_replicas(_sig(burn_fast=9.0), 4, 1, 4) == 4
    # NaN slope (cold trend ring) is treated as flat, not hot
    nan_sig = _sig(p_fast=10.0)
    nan_sig["slope_ms_per_s"] = math.nan
    assert predictive_target_replicas(nan_sig, 3, 1, 4) == 2


# ---------------------------------------------------------------------------
# slo.alert stream validation (tools/check_trace.py --fleet)
# ---------------------------------------------------------------------------


def _fleet_doc_with_alerts(alerts):
    evs = [{"name": "process_name", "ph": "M", "pid": 1,
            "args": {"name": "router"}},
           {"name": "fleet.clock_sync", "ph": "i", "ts": 0.0, "s": "t",
            "pid": 1, "tid": 0, "args": {"unix_ms": 0}}]
    for i, args in enumerate(alerts):
        evs.append({"name": "slo.alert", "ph": "i",
                    "ts": 100.0 + 10.0 * i, "s": "t", "pid": 1,
                    "tid": 0, "args": args})
    return {"traceEvents": evs,
            "fleet": {"processes": {"router": {"pid": 1}}}}


def _alert(prev, state, objective="lat:p99", window="1m"):
    return {"objective": objective, "prev": prev, "state": state,
            "window": window, "burn_fast": 2.0, "burn_slow": 1.5}


def _check(tmp_path, doc):
    from tools.check_trace import check_fleet_trace
    p = tmp_path / "merged.json"
    p.write_text(json.dumps(doc))
    check_fleet_trace(str(p))


def test_check_fleet_accepts_legal_alert_cycle(tmp_path, capsys):
    _check(tmp_path, _fleet_doc_with_alerts([
        _alert("ok", "pending"), _alert("pending", "firing"),
        _alert("firing", "ok"), _alert("ok", "pending"),
        _alert("pending", "ok")]))
    out = capsys.readouterr().out
    assert "5 slo.alert(s)" in out


def test_check_fleet_rejects_tampered_alert_streams(tmp_path, capsys):
    from tools.check_trace import check_fleet_trace  # noqa: F401
    # a firing with no pending before it (ok -> firing jump)
    with pytest.raises(SystemExit):
        _check(tmp_path, _fleet_doc_with_alerts([
            _alert("ok", "firing")]))
    capsys.readouterr()
    # prev does not chain (out-of-order / reordered stream)
    with pytest.raises(SystemExit):
        _check(tmp_path, _fleet_doc_with_alerts([
            _alert("ok", "pending"), _alert("ok", "pending")]))
    capsys.readouterr()
    # firing -> pending shortcut is not a legal hysteresis edge
    with pytest.raises(SystemExit):
        _check(tmp_path, _fleet_doc_with_alerts([
            _alert("ok", "pending"), _alert("pending", "firing"),
            _alert("firing", "pending")]))
    capsys.readouterr()
    # missing attribution fields
    with pytest.raises(SystemExit):
        _check(tmp_path, _fleet_doc_with_alerts([
            {"prev": "ok", "state": "pending", "window": "1m"}]))
    capsys.readouterr()
    with pytest.raises(SystemExit):
        _check(tmp_path, _fleet_doc_with_alerts([
            {"objective": "lat:p99", "prev": "ok",
             "state": "pending"}]))
    capsys.readouterr()


def test_check_fleet_alert_streams_are_per_objective(tmp_path, capsys):
    """Interleaved objectives each chain independently."""
    _check(tmp_path, _fleet_doc_with_alerts([
        _alert("ok", "pending", objective="a:p99"),
        _alert("ok", "pending", objective="b:p95"),
        _alert("pending", "firing", objective="a:p99"),
        _alert("pending", "ok", objective="b:p95"),
        _alert("firing", "ok", objective="a:p99")]))
    capsys.readouterr()


# ---------------------------------------------------------------------------
# slo/ ledger family + gate + ramp record
# ---------------------------------------------------------------------------


def _ramp_steps():
    def step(speed, p99, state, cycles, bf, replicas):
        return {"speed": speed, "level": f"x{speed:g}",
                "metrics": {"p99_ms": p99, "errors": 0, "rejected": 0,
                            "offered_qps": 10.0 * speed},
                "slo": {"replicas": replicas, "objectives": {
                    "lat:p99": {"state": state, "cycles": cycles,
                                "burn_fast": bf, "burn_slow": bf / 2}}}}
    return [step(1, 10.0, "ok", 0, 0.0, 1),
            step(2, 20.0, "ok", 0, 0.5, 2),
            step(4, 30.0, "ok", 0, 0.8, 2)]


def test_ramp_record_summarizes_arm():
    from dmlp_tpu.fleet.loadgen import ramp_record
    rec = ramp_record("predictive", "lat:p99", _ramp_steps(),
                      replicas=1, trace="t.jsonl")
    assert rec.kind == "slo"
    assert rec.config["arm"] == "predictive"
    assert rec.config["levels"] == ["x1", "x2", "x4"]
    m = rec.metrics
    assert m["breach_cycles"] == 0
    assert m["worst_state_level"] == 0
    assert m["max_burn_fast"] == pytest.approx(0.8)
    assert m["replicas_final"] == 2
    assert m["peak_p99_ms"] == 30.0
    # a reactive arm that fired shows it
    steps = _ramp_steps()
    steps[-1]["slo"]["objectives"]["lat:p99"].update(
        state="firing", cycles=0, burn_fast=6.0)
    rec2 = ramp_record("reactive", "lat:p99", steps)
    assert rec2.metrics["breach_cycles"] >= 1
    assert rec2.metrics["worst_state_level"] == 2


def test_slo_records_key_per_arm_series_and_gate():
    from dmlp_tpu.fleet.loadgen import ramp_record
    from tools.perf_gate import gated
    rec = ramp_record("predictive", "lat:p99", _ramp_steps())
    name = _runrecord_series_name(rec, "breach_cycles")
    assert name == "slo/predictive/breach_cycles"
    assert gated(name, _better_direction(name))
    assert _better_direction(name) == "lower"
    assert _better_direction(
        _runrecord_series_name(rec, "max_burn_fast")) == "lower"
    assert _better_direction(
        _runrecord_series_name(rec, "peak_p99_ms")) == "lower"
    rec2 = ramp_record("reactive", "lat:p99", _ramp_steps())
    assert _runrecord_series_name(
        rec2, "breach_cycles") == "slo/reactive/breach_cycles"


def test_slo_ledger_ingests_ramp_records(tmp_path):
    from dmlp_tpu.fleet.loadgen import ramp_record
    from dmlp_tpu.obs.ledger import build_ledger
    rec = ramp_record("predictive", "lat:p99", _ramp_steps())
    rec.round = 17
    rec.append_jsonl(str(tmp_path / "SLO_r17.jsonl"))
    ledger = build_ledger(str(tmp_path))
    assert "slo/predictive/breach_cycles" in ledger["series"]
    pt = ledger["series"]["slo/predictive/breach_cycles"][0]
    assert pt["value"] == 0
    assert pt["round"] == 17
