"""End-to-end multi-host contract run (VERDICT r1 item 2).

Spawns real OS processes that form a jax.distributed CPU cluster (Gloo
collectives), each seeing its own virtual devices — the closest a single
host gets to the reference's 2-node mpirun operating mode
(run_bench.sh:82-84). Process 0's stdout must be byte-identical to the
golden oracle's.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.sharded import ShardedEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import parse_input_text
from dmlp_tpu.io.report import format_results
from dmlp_tpu.parallel.mesh import make_mesh


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn(input_path, port, nprocs, pid, devices_per_proc, extra=()):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}")
    return subprocess.Popen(
        [sys.executable, "-m", "dmlp_tpu.distributed",
         "--input", str(input_path),
         "--coordinator", f"localhost:{port}",
         "--processes", str(nprocs), "--process-id", str(pid), *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.parametrize("extra", [(), ("--select", "topk")])
def test_two_process_contract_run_matches_golden(tmp_path, extra):
    text = generate_input_text(211, 23, 5, -4, 4, 1, 12, 4, seed=9)
    path = tmp_path / "in.txt"
    path.write_text(text)
    want = format_results(knn_golden(parse_input_text(text)))

    port = _free_port()
    procs = [_spawn(path, port, 2, pid, devices_per_proc=2, extra=extra)
             for pid in (0, 1)]
    outs = [p.communicate(timeout=240) for p in procs]
    assert all(p.returncode == 0 for p in procs), \
        [o[1].decode()[-2000:] for o in outs]
    assert outs[0][0].decode() == want          # proc 0: canonical stdout
    assert outs[1][0].decode() == ""            # proc 1: silent
    assert "Time taken:" in outs[0][1].decode()  # contract stderr line


def test_process_slice_matches_addressable_shards():
    """process_slice must agree with what the sharding actually assigns
    (the ADVICE r1 item: no shard_bounds-style process/axis assumptions)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlp_tpu.parallel.distributed import process_slice

    mesh = make_mesh()  # (4, 2) over the 8 virtual devices
    npad = 64
    sh = NamedSharding(mesh, P("data", None))
    lo, hi = process_slice(sh, (npad, 3))
    # single process: the addressable block is the whole axis
    assert (lo, hi) == (0, npad)
    qsh = NamedSharding(mesh, P("query", None))
    assert process_slice(qsh, (16, 3)) == (0, 16)


def test_contract_run_single_process_matches_golden(tmp_path, capsys):
    """The same entry point, degenerate single-process form, all selects."""
    from dmlp_tpu.parallel.distributed import distributed_contract_run

    text = generate_input_text(97, 11, 4, 0, 9, 1, 30, 3, seed=4)
    path = tmp_path / "in.txt"
    path.write_text(text)
    inp = parse_input_text(text)
    want = [r.checksum() for r in knn_golden(inp)]

    for select, dtype in (("sort", "auto"), ("topk", "auto"),
                          ("seg", "auto"), ("topk", "bfloat16")):
        engine = ShardedEngine(
            EngineConfig(mode="sharded", select=select, query_block=8,
                         dtype=dtype),
            mesh=make_mesh())
        got = distributed_contract_run(str(path), engine,
                                       out=open(os.devnull, "w"),
                                       err=open(os.devnull, "w"))
        assert [r.checksum() for r in got] == want, (select, dtype)


def test_distributed_rescore_repairs_duplicate_ties(tmp_path):
    """Adversarial duplicate-heavy data: every point identical, so every
    shard's f32 tie boundary overflows and the per-shard f64 repair path
    must fire — and still match golden."""
    from dmlp_tpu.parallel.distributed import distributed_contract_run

    n, q, a = 96, 8, 3
    lines = [f"{n} {q} {a}"]
    for i in range(n):
        lines.append(" ".join([str(i % 4)] + ["1.000000"] * a))
    for _ in range(q):
        lines.append("Q 7 " + " ".join(["1.000000"] * a))
    text = "\n".join(lines) + "\n"
    path = tmp_path / "dup.txt"
    path.write_text(text)
    inp = parse_input_text(text)
    want = [r.checksum() for r in knn_golden(inp)]

    engine = ShardedEngine(
        EngineConfig(mode="sharded", select="topk", query_block=8,
                     data_block=16),
        mesh=make_mesh())
    got = distributed_contract_run(str(path), engine,
                                   out=open(os.devnull, "w"),
                                   err=open(os.devnull, "w"))
    assert [r.checksum() for r in got] == want


def test_two_process_tiny_input_empty_shard(tmp_path):
    """num_data small enough that one process's padded block holds no real
    rows at all — the all-sentinel shard path must not crash and the
    output must still match golden."""
    text = generate_input_text(10, 5, 3, -2, 2, 1, 10, 3, seed=2)
    path = tmp_path / "tiny.txt"
    path.write_text(text)
    want = format_results(knn_golden(parse_input_text(text)))

    port = _free_port()
    procs = [_spawn(path, port, 2, pid, devices_per_proc=4) for pid in (0, 1)]
    outs = [p.communicate(timeout=240) for p in procs]
    assert all(p.returncode == 0 for p in procs), \
        [o[1].decode()[-2000:] for o in outs]
    assert outs[0][0].decode() == want


def test_four_process_contract_run_matches_golden(tmp_path):
    """VERDICT r2 item 6: beyond 2 processes. 4 procs x 2 devices form a
    (4, 2) mesh, one data-axis row per process."""
    text = generate_input_text(193, 17, 4, -3, 3, 1, 10, 4, seed=13)
    path = tmp_path / "in4.txt"
    path.write_text(text)
    want = format_results(knn_golden(parse_input_text(text)))

    port = _free_port()
    procs = [_spawn(path, port, 4, pid, devices_per_proc=2)
             for pid in (0, 1, 2, 3)]
    outs = [p.communicate(timeout=240) for p in procs]
    assert all(p.returncode == 0 for p in procs), \
        [o[1].decode()[-2000:] for o in outs]
    assert outs[0][0].decode() == want
    assert all(outs[pid][0].decode() == "" for pid in (1, 2, 3))


def test_two_process_four_devices_spans_data_rows(tmp_path):
    """VERDICT r2 item 6: a process owning multiple data-axis rows — 2
    procs x 4 devices on the auto (4, 2) mesh, each process spans two
    rows of the data axis (the exact shape the r1 advisory warned
    shard_bounds-style arithmetic gets wrong)."""
    text = generate_input_text(301, 19, 5, -6, 6, 1, 14, 5, seed=31)
    path = tmp_path / "in24.txt"
    path.write_text(text)
    want = format_results(knn_golden(parse_input_text(text)))

    port = _free_port()
    procs = [_spawn(path, port, 2, pid, devices_per_proc=4)
             for pid in (0, 1)]
    outs = [p.communicate(timeout=240) for p in procs]
    assert all(p.returncode == 0 for p in procs), \
        [o[1].decode()[-2000:] for o in outs]
    assert outs[0][0].decode() == want


def test_process_slice_rejects_non_contiguous_block():
    """The documented error path (VERDICT r2 item 6): a layout whose
    process-addressable shards leave a gap must raise, not feed wrong
    rows."""
    from dmlp_tpu.parallel.distributed import process_slice

    class GappySharding:
        def addressable_devices_indices_map(self, shape):
            return {"d0": (slice(0, 8), slice(None)),
                    "d1": (slice(16, 24), slice(None))}

    with pytest.raises(ValueError, match="not contiguous"):
        process_slice(GappySharding(), (32, 4))


def test_contract_run_hetk_routing_matches_golden(tmp_path):
    """Heterogeneous-k routing on the multi-host path: data placed once,
    bulk queries on the per-shard extraction kernel, wide-k outliers on
    the streaming select with their own query feed; proc-0 output must
    still be byte-identical to golden."""
    from dmlp_tpu.io.grammar import KNNInput, Params, format_input
    from dmlp_tpu.parallel.distributed import distributed_contract_run

    rng = np.random.default_rng(91)
    n, nq, na = 700, 12, 4
    data = rng.uniform(0, 40, (n, na))
    queries = rng.uniform(0, 40, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, 25, nq).astype(np.int32)
    ks[3], ks[9] = 600, 700
    inp = parse_input_text(format_input(
        KNNInput(Params(n, nq, na), labels, data, ks, queries)))
    path = tmp_path / "hetk.txt"
    path.write_text(format_input(inp))
    want = [r.checksum() for r in knn_golden(inp)]

    engine = ShardedEngine(
        EngineConfig(mode="sharded", select="extract", use_pallas=True),
        mesh=make_mesh())
    got = distributed_contract_run(str(path), engine,
                                   out=open(os.devnull, "w"),
                                   err=open(os.devnull, "w"))
    assert [r.query_id for r in got] == list(range(nq))
    assert [r.checksum() for r in got] == want


def test_two_process_hetk_contract_run_matches_golden(tmp_path):
    """The same routed solve across a real 2-process Gloo cluster."""
    from dmlp_tpu.io.grammar import KNNInput, Params, format_input

    rng = np.random.default_rng(92)
    n, nq, na = 640, 8, 3
    data = rng.uniform(0, 30, (n, na))
    queries = rng.uniform(0, 30, (nq, na))
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = rng.integers(1, 20, nq).astype(np.int32)
    ks[5] = 640
    inp = parse_input_text(format_input(
        KNNInput(Params(n, nq, na), labels, data, ks, queries)))
    path = tmp_path / "hetk2.txt"
    path.write_text(format_input(inp))
    want = format_results(knn_golden(inp))

    port = _free_port()
    extra = ("--select", "extract", "--pallas")
    procs = [_spawn(path, port, 2, pid, 4, extra) for pid in range(2)]
    outs = [p.communicate(timeout=420) for p in procs]
    for p, (o, e) in zip(procs, outs):
        assert p.returncode == 0, e.decode()[-2000:]
    assert outs[0][0].decode() == want


def test_contract_run_all_wide_k_f32_staging(tmp_path, monkeypatch):
    """Multi-host path at ALL-wide k (every k > the kernel window): the
    wide-k staging policy (staging_for_k) must govern the contract run
    too — simulate TPU's bf16 auto-resolution and assert the engine is
    swapped to f32 staging inside the solve while output stays golden."""
    from dmlp_tpu.io.grammar import KNNInput, Params, format_input
    from dmlp_tpu.parallel.distributed import distributed_contract_run

    monkeypatch.setattr(EngineConfig, "resolve_dtype",
                        lambda self: "bfloat16" if self.dtype == "auto"
                        else self.dtype)
    rng = np.random.default_rng(95)
    n, nq, na = 1400, 6, 4
    data = rng.uniform(0, 30, (n, na))
    queries = rng.uniform(0, 30, (nq, na))
    labels = rng.integers(0, 4, n).astype(np.int32)
    ks = rng.integers(700, n + 1, nq).astype(np.int32)
    text = format_input(
        KNNInput(Params(n, nq, na), labels, data, ks, queries))
    inp = parse_input_text(text)
    path = tmp_path / "widek.txt"
    path.write_text(text)
    want = [r.checksum() for r in knn_golden(inp)]

    engine = ShardedEngine(EngineConfig(mode="sharded", dtype="auto"),
                           mesh=make_mesh())
    assert engine._staging == "bfloat16"
    seen = {}
    orig = ShardedEngine.solve_local_shards

    def spy(self, *a, **kw):
        seen["staging"] = self._staging
        return orig(self, *a, **kw)

    monkeypatch.setattr(ShardedEngine, "solve_local_shards", spy)
    with open(os.devnull, "w") as devnull:
        got = distributed_contract_run(str(path), engine,
                                       out=devnull, err=devnull)
    assert seen["staging"] == "float32"  # wide-k swap reached the solve
    assert engine._staging == "bfloat16"  # restored
    assert [r.checksum() for r in got] == want
