# Build system — the analog of the reference's Makefile (mpicxx engine /
# engine.debug targets). Here the compiled artifact is the native input
# parser; the engines are JAX programs compiled by XLA at run time.

CXX ?= g++
CXXFLAGS ?= -O3 -Wall -shared -fPIC

.PHONY: all native test bench clean

all: native

native: native/_fastparse.so

native/_fastparse.so: native/fastparse.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

test:
	python -m pytest tests/ -q

# One-line JSON benchmark on the current backend (TPU under the default env).
bench:
	python bench.py

clean:
	rm -f native/_fastparse.so
