# Build system — the analog of the reference's Makefile (mpicxx engine /
# engine.debug targets). Here the compiled artifact is the native input
# parser; the engines are JAX programs compiled by XLA at run time.

CXX ?= g++
CXXFLAGS ?= -O3 -Wall -shared -fPIC

.PHONY: all native test tier1 bench obs-smoke obs-dist-smoke tune-smoke \
	perf-gate check lint chaos-smoke telemetry-smoke serve-smoke \
	race-smoke prune-smoke precision-smoke fleet-smoke \
	fleet-chaos-smoke fleet-trace-smoke slo-smoke auto-smoke \
	hlo-smoke serve-bench fleet-bench clean

all: native

native: native/_fastparse.so

native/_fastparse.so: native/fastparse.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

test: obs-smoke obs-dist-smoke tune-smoke perf-gate check lint \
	chaos-smoke telemetry-smoke serve-smoke race-smoke prune-smoke \
	precision-smoke fleet-smoke fleet-chaos-smoke fleet-trace-smoke \
	slo-smoke auto-smoke hlo-smoke
	python -m pytest tests/ -q

# Static analysis + runtime-sanitizer smoke (README "Static analysis &
# sanitizers"): the AST rule families R1-R7 (collective-axis contract,
# recompilation hazards, host-sync hazards, compat-bypass, resilience
# swallowing, metric names, concurrency discipline) over the whole
# package, gated by check_baseline.json — the committed baseline is EMPTY,
# so ANY finding fails. Results are cached per file content hash under
# ~/.cache/dmlp_tpu/check, so re-runs only re-analyze changed files
# (--no-cache opts out). Then the runtime half: bench config 1 through the
# real CLI under DMLP_TPU_SANITIZE=1 (jax.transfer_guard("disallow") +
# jax.checking_leaks active around the solve) must complete with contract
# stdout byte-identical to the plain run — the hot path is transfer-clean
# end to end, with only the annotated explicit device_get fences reading
# back.
check:
	mkdir -p outputs
	JAX_PLATFORMS=cpu python -m dmlp_tpu.check
	JAX_PLATFORMS=cpu python -c "from dmlp_tpu.bench.configs import BENCH_CONFIGS; \
	from dmlp_tpu.bench.harness import ensure_input; \
	ensure_input(BENCH_CONFIGS[1], 'inputs')"
	JAX_PLATFORMS=cpu DMLP_TPU_SANITIZE= python -m dmlp_tpu \
	  < inputs/input1.in \
	  > outputs/check_plain.out 2> outputs/check_plain.err
	rm -f outputs/check_sanitized_metrics.jsonl
	JAX_PLATFORMS=cpu DMLP_TPU_SANITIZE=1 python -m dmlp_tpu \
	  --trace outputs/check_sanitized_trace.json \
	  --metrics outputs/check_sanitized_metrics.jsonl \
	  < inputs/input1.in \
	  > outputs/check_sanitized.out 2> outputs/check_sanitized.err
	grep -q "Time taken:" outputs/check_sanitized.err
	cmp outputs/check_plain.out outputs/check_sanitized.out
	python tools/check_trace.py outputs/check_sanitized_trace.json \
	  outputs/check_sanitized_metrics.jsonl

# Generic hygiene (the conservative ruff subset, pyproject [tool.ruff]):
# ruff when the environment has it, plus the checker's built-in R0
# family either way — this container ships no ruff, so R0 IS the gate
# here, over the package, tools, tests, and bench.py.
lint:
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check dmlp_tpu tools tests bench.py; \
	else \
	  echo "ruff not installed; R0 family covers the same rule set"; \
	fi
	JAX_PLATFORMS=cpu python -m dmlp_tpu.check --families R0 \
	  --no-baseline dmlp_tpu tools tests bench.py

# Tier-1 no-regression guard (ROADMAP "Tier-1 verify"): on this
# container's jax (0.4.37, CPU backend) the suite must hold >= 277
# passed with the failure set no worse than PR 2's 11 environment-limited
# cases (6 multi-process spawn + 3 offload + 1 multipass-semantics +
# 1 offload-loop — all pre-existing jax/container limits, none
# engine-correctness). Run before merging anything that touches the
# engines, the kernels, or obs.
tier1:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors

# One-line JSON benchmark on the current backend (TPU under the default env).
bench:
	python bench.py

# Observability smoke: run bench config 1 through the real CLI with
# --trace/--metrics on CPU, then validate the artifacts' structural
# contract (Perfetto-loadable spans; summary record with cost-analysis
# counters or the explicit counters_unavailable marker).
obs-smoke:
	mkdir -p outputs
	JAX_PLATFORMS=cpu python -c "from dmlp_tpu.bench.configs import BENCH_CONFIGS; \
	from dmlp_tpu.bench.harness import ensure_input; \
	ensure_input(BENCH_CONFIGS[1], 'inputs')"
	rm -f outputs/obs_metrics.jsonl
	JAX_PLATFORMS=cpu python -m dmlp_tpu --trace outputs/obs_trace.json \
	  --metrics outputs/obs_metrics.jsonl < inputs/input1.in \
	  > outputs/obs_smoke.out 2> outputs/obs_smoke.err
	grep -q "Time taken:" outputs/obs_smoke.err
	python tools/check_trace.py outputs/obs_trace.json outputs/obs_metrics.jsonl

# Distributed-observability smoke: a 2-process CPU cluster (emulated
# ranks where the jax build lacks multi-process CPU computations) runs
# the contract entry with per-rank tracing; tools/merge_traces.py merges
# the rank files and tools/check_trace.py --dist validates the merged
# Perfetto trace (distinct rank pids, clock-sync markers, monotonic
# per-rank timestamps).
obs-dist-smoke:
	JAX_PLATFORMS=cpu python tools/obs_dist_smoke.py --dir outputs/dist_obs

# Autotuner smoke: a tiny-shape measured sweep on CPU (interpret-mode
# kernel) through the real `python -m dmlp_tpu.tune` CLI into a
# scratch cache, then an explicit schema validation of the file it
# wrote — proves measure -> pick -> persist -> reload end to end
# without touching any developer's real variant cache.
tune-smoke:
	mkdir -p outputs
	rm -f outputs/tune_smoke_cache.json
	JAX_PLATFORMS=cpu DMLP_TPU_TUNE_CACHE=outputs/tune_smoke_cache.json \
	  python -m dmlp_tpu.tune --smoke --record outputs/TUNE_SMOKE.json
	JAX_PLATFORMS=cpu python -m dmlp_tpu.tune \
	  --validate outputs/tune_smoke_cache.json

# Perf ledger + regression sentinel: build the ledger over every root
# artifact (schema RunRecords + grandfathered legacy shapes; >= 90%
# parsed or the smoke fails, the rest explicit unparseable entries),
# write the trajectory report, then gate tracked series — a round that
# regresses a gated series beyond its noise band on comparable devices
# fails the build (honest insufficient_trials / device_mismatch
# markers never do).
perf-gate:
	mkdir -p outputs
	JAX_PLATFORMS=cpu python -m dmlp_tpu.report \
	  --out outputs/LEDGER.json --md outputs/PERF_REPORT.md \
	  --min-coverage 0.9
	JAX_PLATFORMS=cpu python tools/perf_gate.py \
	  --ledger outputs/LEDGER.json

# Chaos smoke (README "Resilience & chaos testing"): bench config 1 and
# a short --nan-guard train run replayed under three seeded fault
# schedules (straggler delays, transient exceptions + corrupt parse,
# simulated RESOURCE_EXHAUSTED driving the degradation ladder). Every
# faulted run's output must be BYTE-IDENTICAL to the fault-free golden
# run, faults must actually fire, recovery must be visible in the
# resilience counters and resilience.* trace events, one schedule must
# replay with a bit-identical injection log, and the zero-fault overhead
# of the wrappers is measured with an interleaved on/off A/B into a
# ledger-ingestible RunRecord.
chaos-smoke:
	mkdir -p outputs/chaos
	rm -f outputs/chaos/CHAOS_SMOKE.jsonl
	JAX_PLATFORMS=cpu python tools/chaos_run.py --smoke \
	  --out outputs/chaos --record outputs/chaos/CHAOS_SMOKE.jsonl

# Live-telemetry smoke (README "Live telemetry, memory watermarks &
# flight recorder"): bench config 1 through the real CLI in interleaved
# --telemetry on/off pairs — contract stdout byte-identical, the
# OpenMetrics snapshot structurally valid (with the honest
# mem.stats_unavailable gauge on this CPU backend), the analytic
# peak-HBM model reconciled against the measured watermark within the
# documented basis bounds (or the explicit marker), a FLIGHT_*.json
# post-mortem left by a retries-exhausted fault, and the overhead +
# watermark numbers round-tripped through the perf ledger as a
# telemetry/ series with raw per-arm samples.
telemetry-smoke:
	mkdir -p outputs/telemetry
	rm -f outputs/telemetry/TELEMETRY_SMOKE.jsonl
	JAX_PLATFORMS=cpu python tools/telemetry_smoke.py \
	  --out outputs/telemetry \
	  --record outputs/telemetry/TELEMETRY_SMOKE.jsonl

# Online-serving smoke (README "Serving"): the real daemon subprocess
# on a scratch corpus — warmed shape buckets with the cold-start number
# in the ready file, a mixed-(nq, k) trace replayed over concurrent
# connections with every response byte-identical to the golden oracle,
# the compile counter pinned across the replay (no per-request
# recompilation), a valid OpenMetrics scrape from --telemetry-port, an
# injected memory squeeze shed by admission control (visible rejection,
# no ladder degradation), wire ingestion verified against the grown
# corpus, and a SIGTERM drain that exits 0 with no flight dump — with
# the serve RunRecord round-tripped through the perf ledger.
serve-smoke:
	mkdir -p outputs/serve
	rm -f outputs/serve/SERVE_SMOKE.jsonl
	JAX_PLATFORMS=cpu python tools/serve_smoke.py --out outputs/serve \
	  --record outputs/serve/SERVE_SMOKE.jsonl

# Concurrency-discipline smoke (README "Static analysis & sanitizers",
# rule family R7): the lock-order / guarded-field / blocking-under-lock
# / thread-lifecycle analyzer must be clean over the whole package with
# no baseline, then tools/race_stress.py proves the runtime half — the
# race sanitizer first catches a SEEDED inversion and sleep-under-lock
# (teeth), then the live daemon is hammered by concurrent query +
# ingest + stats + scrape workers with the Sampler and fault injection
# running: every stressed response must be byte-identical to the golden
# oracle and the sanitizer's verdict over the real system must be
# empty (zero inversions, zero blocking calls under a lock).
race-smoke:
	mkdir -p outputs/race
	JAX_PLATFORMS=cpu python -m dmlp_tpu.check --families R7 \
	  --no-baseline
	JAX_PLATFORMS=cpu python tools/race_stress.py --out outputs/race

# Pruned two-stage solve smoke (README "Pruned two-stage solve"): a
# norm-banded corpus through the real CLI in DMLP_TPU_PRUNE=1/0 arms —
# both byte-identical to the f64 golden model, the pruned arm must
# prune > 0.5 of the blocks and stream < 0.5x the dense bytes (read
# from the metrics summary's prune block), scan.bytes_streamed must be
# visible in the OpenMetrics scrape, and a seeded oom schedule must
# step the degrade ladder prune->fused with byte-identical recovery.
# Then the capacity tool's --cpu-smoke proves the same scanned-bytes
# ratio on its banded beyond-HBM stand-in shape.
prune-smoke:
	mkdir -p outputs/prune
	JAX_PLATFORMS=cpu python tools/prune_smoke.py --out outputs/prune
	JAX_PLATFORMS=cpu BENCH_OUT=outputs/prune/CAPACITY_PRUNE_SMOKE.json \
	  python tools/capacity_beyond_hbm.py --cpu-smoke > /dev/null

# Low-precision first-pass smoke (README "Low-precision first pass"):
# on the banded corpus, forced-bf16 and kill-switch-f32 CLI runs must
# be byte-identical to each other and to the f64 golden model; the
# bf16 arm's metrics must show an ACTIVE bf16 pass with a widened
# (kcap-inflated) rescore window; and a seeded staging oom must step
# the degrade ladder lowp->prune with byte-identical recovery.
precision-smoke:
	mkdir -p outputs/precision
	JAX_PLATFORMS=cpu python tools/precision_smoke.py \
	  --out outputs/precision

# Serving-fleet smoke (README "Fleet serving"): a REAL fleet on CPU —
# a plain resident replica + a mesh-resident replica (--mesh 2x1,
# per-shard resident chunk buffers, allgather merge as the micro-batch
# epilogue) behind the `python -m dmlp_tpu.fleet` router. Eight
# proofs: both replicas warm and announce; the committed paced trace
# (inputs/serve_trace2.jsonl) replayed closed-loop THROUGH the router
# is byte-identical to the golden oracle with traffic actually fanned;
# compile counters stay flat on both replicas; paced OPEN-LOOP replay
# at two offered-load multipliers lands p50/p95/p99 in gated
# fleet/<level>/ ledger series; a wide-k request (k past the kernel's
# single-pass window) serves through the multipass driver against the
# resident chunks, golden and compile-flat; one ingest through the
# router fans out to every replica and the grown-corpus replay stays
# golden with zero new compiles; the router's /metrics merges both
# replicas' scrapes into one valid OpenMetrics exposition (counters
# summed, histograms bucket-wise, per-replica gauges) and the serve
# trace validator rejects non-monotonic t_ms; one in-band drain
# propagates router -> replicas with every process exiting 0 and no
# flight dumps.
fleet-smoke:
	mkdir -p outputs/fleet
	rm -f outputs/fleet/FLEET_SMOKE.jsonl
	JAX_PLATFORMS=cpu python tools/fleet_smoke.py --out outputs/fleet \
	  --record outputs/fleet/FLEET_SMOKE.jsonl

# Self-healing-fleet chaos smoke (README "Fleet self-healing"): three
# seeded failure campaigns over REAL fleets on CPU, every served
# response byte-identical to the golden oracle throughout. (1) A
# SUPERVISED fleet (the router spawns/owns 2 mesh-2x1 replicas): one
# replica SIGKILLed mid-replay — every in-flight response still golden
# via bounded retry, the supervisor detects the death and relaunches
# within its budget, the revived fleet serves golden. (2) Far-row
# ingest pushes both replicas past the capacity-buffer threshold while
# open-loop traffic keeps firing: the supervisor stages one shard
# re-split at a time (grown-layout replacement, checksum-verified
# corpus replay, routing-table swap, old replica drained rc 0) until
# the whole fleet runs the doubled capacity — zero lost responses,
# post-split replay golden on the grown corpus. (3) A seeded
# serve.ingest transient fault (the PR 7 injection machinery) drops
# one replica's ingest: the router reports the divergence, the health
# prober's corpus-checksum comparison detects it, and the targeted
# delta re-ingest repairs it — counters non-vacuous, repaired fleet
# golden, every process exits 0, no flight dumps. The chaos RunRecords
# round-trip the perf ledger as gated fleet/chaos_*/ series
# (FLEET_CHAOS_r15.jsonl is the committed round).
fleet-chaos-smoke:
	mkdir -p outputs/fleet_chaos
	rm -f outputs/fleet_chaos/FLEET_CHAOS_SMOKE.jsonl
	JAX_PLATFORMS=cpu python tools/fleet_chaos_smoke.py \
	  --out outputs/fleet_chaos \
	  --record outputs/fleet_chaos/FLEET_CHAOS_SMOKE.jsonl

# Request-tracing smoke (README "Request tracing & tail attribution"):
# five proofs over a REAL 2-replica fleet on CPU. (1) Untraced arm:
# responses carry no rid and checksum golden. (2) Traced arm (x2 + x8
# open-loop replay, rid-stamped client + traced router + replicas):
# every rid echoed, contract checksums BYTE-IDENTICAL to the untraced
# arm. (3) merge_traces --fleet clock-aligns the four per-process
# traces and reconstructs one x8 request client->route->hop->
# queue->coalesce->solve->finalize->write, phase sums reconciling with
# client latency within tolerance. (4) check_trace --fleet passes the
# merged trace and REJECTS a tampered one (fabricated retry hop).
# (5) tail_attrib names each level's dominant phase and its
# fleet/<level>/phase/*_p99_ms RunRecords ledger-ingest and perf-gate
# (TAILATTRIB_r16.jsonl is the committed round).
fleet-trace-smoke:
	mkdir -p outputs/fleet_trace
	rm -f outputs/fleet_trace/TAILATTRIB.jsonl
	JAX_PLATFORMS=cpu python tools/fleet_trace_smoke.py \
	  --out outputs/fleet_trace \
	  --record outputs/fleet_trace/TAILATTRIB.jsonl

# Streaming SLO engine smoke (README "SLO objectives & predictive
# autoscaling"): (1) a seeded breach on a deterministic clock fires
# exactly one ok->pending->firing->ok alert cycle with the
# FLIGHT_slo_breach_* dump + slo_* OpenMetrics families; (2) a
# predictive-vs-reactive ramp A/B over a real supervised fleet — the
# serve.solve delay fault makes replica capacity sleep-bound, the
# reactive watermark arm rides one replica into a p99 breach (its
# slo.alert stream validated by check_trace --fleet after the causal
# merge) while the predictive arm follows the canary burn rate and
# scales ahead of the hot level with zero customer-objective burn;
# both arms byte-identical to the golden oracle, both ramp RunRecords
# ledger-ingested as gated slo/<arm>/ series (SLO_r17.jsonl is the
# committed round).
slo-smoke:
	mkdir -p outputs/slo
	rm -f outputs/slo/SLO_SMOKE.jsonl
	JAX_PLATFORMS=cpu python tools/slo_smoke.py \
	  --out outputs/slo \
	  --record outputs/slo/SLO_SMOKE.jsonl

# Compiler-sharded engine smoke (README "Compiler-driven sharding &
# persistent compile cache"): (1) the `--engine auto` CLI alias
# end-to-end on bench input 1 — contract stdout byte-identical to the
# default single-chip run (and hence to the f64 golden oracle the
# bench step diffs below); (2) bench --auto-ab on config 1:
# interleaved auto/sharded/ring arms with byte-identity asserted
# before any timing enters the record and the warmup-compile split
# broken out per arm; (3) the kind="auto" RunRecord round-trips the
# perf ledger as a gated auto/config1/ series. The warm-relaunch
# cold-start check (persistent compile cache) lives in
# fleet-chaos-smoke campaign 4.
auto-smoke:
	mkdir -p outputs/auto
	JAX_PLATFORMS=cpu python -c "from dmlp_tpu.bench.configs import BENCH_CONFIGS; \
	from dmlp_tpu.bench.harness import ensure_input; \
	ensure_input(BENCH_CONFIGS[1], 'inputs')"
	JAX_PLATFORMS=cpu python -m dmlp_tpu < inputs/input1.in \
	  > outputs/auto/single.out 2> /dev/null
	JAX_PLATFORMS=cpu python -m dmlp_tpu --engine auto \
	  < inputs/input1.in \
	  > outputs/auto/auto.out 2> outputs/auto/auto.err
	grep -q "Time taken:" outputs/auto/auto.err
	cmp outputs/auto/single.out outputs/auto/auto.out
	rm -f outputs/auto/AUTO_SMOKE.jsonl
	JAX_PLATFORMS=cpu python -m dmlp_tpu.bench 1 --auto-ab --reps 2 \
	  --metrics outputs/auto/AUTO_SMOKE.jsonl \
	  | tee outputs/auto/bench.out
	grep -q "byte-identical" outputs/auto/bench.out
	JAX_PLATFORMS=cpu python -c "import sys; \
	from dmlp_tpu.obs.ledger import ingest_file; \
	e = ingest_file('outputs/auto/AUTO_SMOKE.jsonl'); \
	assert e['status'] == 'parsed', e; \
	s = {p['series'] for p in e['points']}; \
	assert any(x.startswith('auto/config1/') for x in s), sorted(s); \
	sys.path.insert(0, 'tools'); import perf_gate as pg; \
	assert pg.gated('auto/config1/engine_ms_auto')"

# Compiled-program introspection smoke (README "Compiler
# introspection"): bench input 1 through the real CLI per engine mode
# (sharded / ring / auto) with --hlo-report — contract stdout
# byte-identical to the plain run; the sharded engine's compiled
# all-gather bytes and the ring engine's compiled collective-permute
# bytes (while-loop trip counts folded in) reconcile against their own
# analytic comms models within COMMS_RATIO_BOUNDS; the auto (GSPMD)
# engine's report names at least one partitioner-chosen collective
# with nonzero per-mesh-axis bytes and exactly-reconciling gspmd_*
# records; the memory leg carries hlo_peak_bytes or the explicit
# hlo_memory_unavailable marker; and each kind="hlo" RunRecord
# round-trips the ledger as a gated hlo/<mode>/ series
# (HLO_r20.jsonl is the committed round).
hlo-smoke:
	mkdir -p outputs/hlo
	JAX_PLATFORMS=cpu python tools/hlo_smoke.py --out outputs/hlo

# Fleet SLO bench (not in `make test`; emits the FLEET_rNN ledger
# rounds): 2 replicas (one mesh-resident) + router, the paced trace
# replayed OPEN-LOOP at a sweep of offered-load multipliers, 3 reps
# per level — the p99-under-offered-load curve, gated by perf_gate.
# On a TPU host drop JAX_PLATFORMS and add
# --replica-flags "--pallas --select extract".
fleet-bench:
	mkdir -p outputs/fleet_bench
	JAX_PLATFORMS=cpu python tools/fleet_bench.py \
	  --metrics outputs/fleet_bench/FLEET_BENCH.jsonl \
	  --out outputs/fleet_bench --replicas 2 --mesh-replica --reps 3

# Serving throughput bench (not in `make test`; emits the SERVE_rNN
# ledger rounds): replay inputs/serve_trace1.jsonl against the daemon
# in interleaved gate-carry on/off arms. On a TPU host drop
# JAX_PLATFORMS and keep the pallas flags.
serve-bench:
	mkdir -p outputs
	python -m dmlp_tpu.bench serve --reps 2 \
	  --metrics outputs/SERVE_BENCH.jsonl \
	  --serve-flags "--pallas --select extract --data-block 12800"

clean:
	rm -f native/_fastparse.so
